"""Fault-tolerance tests: chaos harness, retry paths, watchdog ladder,
CheckpointManager rollback/atomicity, CRC-verified IO.

Every recovery claim is asserted against an *observed* injection (the
chaos.inject counter) — never against luck.
"""
import json
import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu import observability
from paddle_tpu.core import flags
from paddle_tpu.core.enforce import DataLossError, UnavailableError
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.distributed import comm_watchdog as cw
from paddle_tpu.distributed.fault_tolerance import (ChaosCollectiveTimeout,
                                                    CheckpointManager, chaos)
from paddle_tpu.distributed.store import TCPStore

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _chaos_isolation():
    """Chaos specs and watchdog policies must never leak between tests."""
    yield
    chaos.reconfigure("")
    flags.set_flags({"watchdog_policy": "", "comm_timeout": 0.0,
                     "comm_watchdog_abort": True})


def _metric(name, labels=None):
    return observability.registry().value(name, labels or {})


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _mlp(seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 4))


# ---------------------------------------------------------------------------
# Spec parsing
# ---------------------------------------------------------------------------

def test_parse_spec_selectors():
    injs = chaos.parse_spec(
        "dispatch:nan@op=mean;step=3;count=2, collective:timeout, "
        "store:garble@op=get;prob=0.5, fetch:stall@delay=0.2")
    assert [(i.site, i.kind) for i in injs] == [
        ("dispatch", "nan"), ("collective", "timeout"),
        ("store", "garble"), ("fetch", "stall")]
    assert injs[0].op == "mean" and injs[0].step == 3 and injs[0].count == 2
    assert injs[2].prob == 0.5
    assert injs[3].delay == 0.2
    assert chaos.parse_spec("") == []


@pytest.mark.parametrize("bad", [
    "dispatch",                # no kind
    "dispatch:frobnicate",     # unknown kind
    "warp:nan",                # unknown site
    "dispatch:nan@bogus=1",    # unknown selector
    "dispatch:nan@step=x",     # non-int selector value
])
def test_parse_spec_malformed_raises(bad):
    with pytest.raises(ValueError):
        chaos.parse_spec(bad)


def test_flag_activation_installs_and_removes_hooks():
    from paddle_tpu.ops import dispatch

    flags.set_flags({"chaos_spec": "dispatch:nan@op=nosuchop"})
    try:
        assert dispatch._chaos_hook[0] is not None
        assert chaos.active()
    finally:
        flags.set_flags({"chaos_spec": ""})
    assert dispatch._chaos_hook[0] is None
    assert not chaos.active()


# ---------------------------------------------------------------------------
# Dispatch poisoning
# ---------------------------------------------------------------------------

def test_dispatch_nan_poison_op_and_count():
    before = _metric("paddle_chaos_injections_total",
                     {"site": "dispatch", "kind": "nan"})
    chaos.reconfigure("dispatch:nan@op=add;count=1")
    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    poisoned = a + a
    clean = a + a  # count=1: second call untouched
    assert np.isnan(poisoned.numpy()).all()
    np.testing.assert_allclose(clean.numpy(), 2.0)
    assert _metric("paddle_chaos_injections_total",
                   {"site": "dispatch", "kind": "nan"}) == before + 1


def test_dispatch_inf_poison():
    chaos.reconfigure("dispatch:inf@op=subtract")
    a = paddle.to_tensor(np.ones(3, np.float32))
    assert np.isinf((a - a).numpy()).all()


def test_dispatch_rank_dead_revokes_lease_result_untouched():
    """dispatch:rank_dead is the mid-step death drill: the victim's lease
    is revoked through the kill hook but the op result is NOT poisoned —
    the failure surfaces at the next collective/membership poll."""
    seen = []
    prev = chaos.set_rank_kill_hook(lambda victim, site: seen.append((victim,
                                                                      site)))
    try:
        chaos.reconfigure("dispatch:rank_dead@op=add;victim=1;count=1")
        a = paddle.to_tensor(np.ones((2, 2), np.float32))
        np.testing.assert_allclose((a + a).numpy(), 2.0)
    finally:
        chaos.set_rank_kill_hook(prev)
    assert seen == [(1, "dispatch")]


def test_save_rank_dead_kills_lease_but_write_completes(tmp_path):
    """save:rank_dead revokes the victim's lease mid-checkpoint while the
    local write still lands intact (unlike save:crash, which hard-exits)."""
    seen = []
    prev = chaos.set_rank_kill_hook(lambda victim, site: seen.append((victim,
                                                                      site)))
    try:
        chaos.reconfigure("save:rank_dead@op=paddle_save;victim=2;count=1")
        path = str(tmp_path / "drill.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(3, np.float32))}, path)
    finally:
        chaos.set_rank_kill_hook(prev)
    assert seen == [(2, "save")]
    loaded = paddle.load(path)
    np.testing.assert_allclose(loaded["w"].numpy(), 1.0)


def test_step_selector_uses_chaos_clock():
    chaos.reconfigure("dispatch:nan@op=add;step=2")
    a = paddle.to_tensor(np.ones(2, np.float32))
    assert np.isfinite((a + a).numpy()).all()   # clock at 0
    chaos.note_step(2)
    assert np.isnan((a + a).numpy()).all()      # clock at 2 → fires


def test_prob_injection_is_seeded_deterministic():
    def pattern():
        flags.set_flags({"chaos_seed": 1234})
        chaos.reconfigure("dispatch:nan@op=add;prob=0.5;count=0")
        a = paddle.to_tensor(np.ones(2, np.float32))
        return [bool(np.isnan((a + a).numpy()).any()) for _ in range(12)]

    first, second = pattern(), pattern()
    assert first == second
    assert any(first) and not all(first)  # prob strictly between 0 and 1


def test_fetch_stall_delays_scalar_fetch():
    a = paddle.to_tensor(np.ones((), np.float32))
    chaos.reconfigure("fetch:stall@delay=0.2")
    t0 = time.perf_counter()
    float(a + a)
    assert time.perf_counter() - t0 >= 0.15


# ---------------------------------------------------------------------------
# Collective retry
# ---------------------------------------------------------------------------

def test_collective_timeout_retried_once():
    before = _metric("paddle_collective_retries_total", {"op": "all_reduce"})
    chaos.reconfigure("collective:timeout@op=all_reduce;count=1")
    t = paddle.to_tensor(np.ones(4, np.float32))
    dist.all_reduce(t)
    np.testing.assert_allclose(t.numpy(), 1.0)  # world=1 identity
    assert _metric("paddle_collective_retries_total",
                   {"op": "all_reduce"}) == before + 1


def test_collective_delay_perturbs_but_completes():
    """collective:delay is the benign latency drill: the op slows down,
    nothing breaks, and no retry is consumed."""
    before = _metric("paddle_chaos_injections_total",
                     {"site": "collective", "kind": "delay"})
    retries = _metric("paddle_collective_retries_total", {"op": "all_reduce"})
    chaos.reconfigure("collective:delay@op=all_reduce;delay=0.15;count=1")
    t = paddle.to_tensor(np.ones(4, np.float32))
    t0 = time.perf_counter()
    dist.all_reduce(t)
    assert time.perf_counter() - t0 >= 0.1
    np.testing.assert_allclose(t.numpy(), 1.0)
    assert _metric("paddle_chaos_injections_total",
                   {"site": "collective", "kind": "delay"}) == before + 1
    assert _metric("paddle_collective_retries_total",
                   {"op": "all_reduce"}) == retries


def test_collective_retries_exhausted_raises():
    flags.set_flags({"collective_retries": 1,
                     "collective_retry_backoff": 0.01})
    try:
        chaos.reconfigure("collective:timeout@op=all_reduce;count=0")
        t = paddle.to_tensor(np.ones(4, np.float32))
        with pytest.raises(ChaosCollectiveTimeout):
            dist.all_reduce(t)
    finally:
        flags.set_flags({"collective_retries": 2,
                         "collective_retry_backoff": 0.05})


# ---------------------------------------------------------------------------
# TCPStore resilience
# ---------------------------------------------------------------------------

@pytest.fixture()
def store_pair():
    port = _free_port()
    master = TCPStore("127.0.0.1", port, is_master=True, world_size=1,
                      use_native=False)
    client = TCPStore("127.0.0.1", port, is_master=False, world_size=1,
                      use_native=False)
    yield master, client
    chaos.reconfigure("")
    client.stop()
    master.stop()


def test_store_delay_slows_request_without_retry(store_pair):
    """store:delay stretches one request's latency; the reply still lands,
    so no retry (and no reconnect) is burned."""
    _, client = store_pair
    client.set("k0", b"v0")
    retries = _metric("paddle_store_retries_total", {"op": "get"})
    chaos.reconfigure("store:delay@op=get;delay=0.15;count=1")
    t0 = time.perf_counter()
    assert client.get("k0") == b"v0"
    assert time.perf_counter() - t0 >= 0.1
    assert _metric("paddle_store_retries_total", {"op": "get"}) == retries


def test_store_drop_reconnects_and_retries(store_pair):
    _, client = store_pair
    client.set("k", b"v1")
    before = _metric("paddle_store_retries_total", {"op": "get"})
    chaos.reconfigure("store:drop@op=get;count=1")
    assert client.get("k") == b"v1"
    assert _metric("paddle_store_retries_total",
                   {"op": "get"}) == before + 1


def test_store_garbled_reply_detected_and_retried(store_pair):
    _, client = store_pair
    client.set("k", b"payload")
    chaos.reconfigure("store:garble@op=get;count=1")
    assert client.get("k") == b"payload"


def test_store_wait_survives_drop(store_pair):
    master, client = store_pair
    chaos.reconfigure("store:drop@op=check;count=1")
    master.set("ready", b"1")
    client.wait("ready", timeout=10.0)  # check() path retries internally


def test_store_set_retried_value_idempotent(store_pair):
    """set is last-writer-wins, so replaying the same write after an
    ambiguous failure converges — it rides the retry path now."""
    _, client = store_pair
    before = _metric("paddle_store_retries_total", {"op": "set"})
    chaos.reconfigure("store:drop@op=set;count=1")
    client.set("k2", b"x")
    assert client.get("k2") == b"x"
    assert _metric("paddle_store_retries_total",
                   {"op": "set"}) == before + 1


def test_store_add_idempotent_token_no_double_count(store_pair):
    """add carries a per-call idempotency token: a retry after a lost
    reply must not double-count (the server replays the recorded
    result)."""
    _, client = store_pair
    assert client.add("ctr", 5) == 5
    before = _metric("paddle_store_retries_total", {"op": "add"})
    chaos.reconfigure("store:drop@op=add;count=1")
    v = client.add("ctr", 3)
    assert v == 8  # exactly one application across the retry
    assert client.add("ctr", 1) == 9
    assert _metric("paddle_store_retries_total",
                   {"op": "add"}) == before + 1


def test_store_add_token_replay_returns_recorded_result(store_pair):
    """The wire-level dedup contract: replaying the same token returns
    the recorded result instead of re-applying the delta."""
    _, client = store_pair
    token = b"\x01" * 16
    assert client._client.add_token("tok", 7, token) == 7
    assert client._client.add_token("tok", 7, token) == 7  # replay
    assert client._client.add_token("tok", 7, b"\x02" * 16) == 14


# ---------------------------------------------------------------------------
# Watchdog escalation ladder
# ---------------------------------------------------------------------------

@pytest.fixture()
def no_abort(monkeypatch):
    killed = []
    monkeypatch.setattr(cw.os, "kill", lambda pid, sig: killed.append(sig))
    return killed


def _expire_once(mgr, timeout=0.25, deadline=8.0, stop=None):
    tid = mgr.start_task("all_reduce", 0, 0, (4,), "float32",
                         timeout=timeout)
    t0 = time.time()
    while time.time() - t0 < deadline:
        if stop is not None and stop():
            break
        time.sleep(0.1)
    mgr.end_task(tid)


def test_ladder_runs_every_stage_then_aborts(no_abort, capfd):
    flags.set_flags({"watchdog_policy": "warn,dump,retry,restart,abort",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    before = {s: _metric("paddle_watchdog_escalations_total", {"stage": s})
              for s in ("warn", "dump", "retry", "restart", "abort")}
    _expire_once(mgr, timeout=0.25, deadline=15.0,
                 stop=lambda: bool(no_abort))
    assert no_abort == [signal.SIGABRT]
    for s in ("warn", "dump", "retry", "restart", "abort"):
        assert _metric("paddle_watchdog_escalations_total",
                       {"stage": s}) == before[s] + 1, s
    err = capfd.readouterr().err
    assert "stage=warn" in err
    assert "stage=dump" in err
    assert "doubled timeout" in err
    assert "gang-restart barrier" in err
    assert "COLLECTIVE TIMEOUT" in err
    assert not mgr.in_flight()


def test_ladder_warn_only_policy_never_aborts(no_abort):
    flags.set_flags({"watchdog_policy": "warn",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    before = _metric("paddle_watchdog_escalations_total", {"stage": "warn"})
    _expire_once(mgr, timeout=0.25, deadline=1.2)
    assert not no_abort
    # last-stage clamp: warn repeats on every successive expiry
    assert _metric("paddle_watchdog_escalations_total",
                   {"stage": "warn"}) >= before + 2


def test_ladder_retry_stage_doubles_timeout(no_abort):
    flags.set_flags({"watchdog_policy": "retry",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    tid = mgr.start_task("all_gather", 0, 0, (2,), "float32", timeout=0.25)
    t0 = time.time()
    while time.time() - t0 < 5.0 and not any(
            t.timeout > 0.3 for t in mgr.in_flight()):
        time.sleep(0.1)
    tasks = mgr.in_flight()
    assert tasks and tasks[0].timeout >= 0.5
    mgr.end_task(tid)


def test_legacy_empty_policy_single_report(no_abort, capfd):
    flags.set_flags({"watchdog_policy": "", "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    _expire_once(mgr, timeout=0.25, deadline=1.0)
    err = capfd.readouterr().err
    assert err.count("COLLECTIVE TIMEOUT") == 1  # popped on first expiry
    assert not no_abort  # abort flag honored


def test_legacy_abort_flag_fires_sigabrt(no_abort):
    flags.set_flags({"watchdog_policy": "", "comm_watchdog_abort": True})
    mgr = cw.CommTaskManager()
    _expire_once(mgr, timeout=0.25, deadline=8.0,
                 stop=lambda: bool(no_abort))
    assert no_abort == [signal.SIGABRT]


def test_unknown_policy_stage_ignored(no_abort, capfd):
    cw._policy_warned[0] = False
    # deliberately bogus stage  # tpu-lint: disable=TPL009
    flags.set_flags({"watchdog_policy": "frobnicate,warn",
                     "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    before = _metric("paddle_watchdog_escalations_total", {"stage": "warn"})
    _expire_once(mgr, timeout=0.25, deadline=1.0)
    err = capfd.readouterr().err
    assert "frobnicate" in err
    assert _metric("paddle_watchdog_escalations_total",
                   {"stage": "warn"}) > before


# ---------------------------------------------------------------------------
# CheckpointManager: rollback, disk protocol, preemption
# ---------------------------------------------------------------------------

def _train(model, opt, cm, x, y, steps, all_reduce_loss=False):
    losses = []
    done = 0
    guard = 0
    while done < steps:
        guard += 1
        assert guard < steps * 5, "rollback loop did not converge"
        out = model(x)
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if all_reduce_loss:
            # stand-in for gradient sync: one collective per step
            sync = paddle.to_tensor(np.ones(2, np.float32))
            dist.all_reduce(sync)
        if cm.on_step(loss):
            continue  # poisoned step rolled back: re-run it
        losses.append(float(loss))
        done += 1
    return losses


def test_e2e_chaos_training_loop(tmp_path):
    """The acceptance drill: one injected collective timeout + one NaN step
    in a short training loop → finite loss, exactly one rollback and one
    collective retry observed, final checkpoint loads with CRC verify."""
    model = _mlp(seed=0)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    cm = CheckpointManager(directory=str(tmp_path), model=model,
                           optimizer=opt, interval=2, async_save=False)
    rb_before = _metric("paddle_ckpt_rollbacks_total")
    cr_before = _metric("paddle_collective_retries_total",
                        {"op": "all_reduce"})
    chaos.reconfigure("dispatch:nan@op=mean;step=3;count=1, "
                      "collective:timeout@op=all_reduce;count=1")
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
    losses = _train(model, opt, cm, x, y, steps=8, all_reduce_loss=True)

    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # it actually trained
    assert _metric("paddle_ckpt_rollbacks_total") == rb_before + 1
    assert _metric("paddle_collective_retries_total",
                   {"op": "all_reduce"}) == cr_before + 1
    assert cm.rollbacks_total == 1

    # final checkpoint loads cleanly (CRC verified inside load_state_dict)
    trained = {k: v.numpy().copy() for k, v in model.state_dict().items()}
    model2 = _mlp(seed=9)
    opt2 = paddle.optimizer.SGD(learning_rate=0.05,
                                parameters=model2.parameters())
    cm2 = CheckpointManager(directory=str(tmp_path), model=model2,
                            optimizer=opt2, interval=2, async_save=False)
    step = cm2.load_latest()
    assert step == 8
    for k, v in model2.state_dict().items():
        np.testing.assert_allclose(v.numpy(), trained[k], rtol=1e-6,
                                   err_msg=k)


def test_rollback_restores_exact_state():
    model = _mlp(seed=1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    cm = CheckpointManager(model=model, optimizer=opt, interval=0)
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4, 4), np.float32))
    _train(model, opt, cm, x, y, steps=2)
    good = {k: v.numpy().copy() for k, v in model.state_dict().items()}
    good_opt_step = opt._step_count

    chaos.reconfigure("dispatch:nan@op=mean;count=1")
    out = model(x)
    loss = ((out - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert cm.on_step(loss) is True  # rolled back
    for k, v in model.state_dict().items():
        np.testing.assert_allclose(v.numpy(), good[k], err_msg=k)
    assert opt._step_count == good_opt_step


def test_rollback_budget_exhausted_raises():
    model = _mlp(seed=2)
    cm = CheckpointManager(model=model, interval=0, rollback_budget=2)
    bad = paddle.to_tensor(np.float32(np.nan))
    assert cm.on_step(bad) is True
    assert cm.on_step(bad) is True
    with pytest.raises(UnavailableError, match="rollback"):
        cm.on_step(bad)


def test_keep_k_gc_and_latest_pointer(tmp_path):
    model = _mlp(seed=3)
    cm = CheckpointManager(directory=str(tmp_path), model=model,
                           interval=1, keep=2, async_save=False)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(5):
        model(x)
        cm.on_step(paddle.to_tensor(np.float32(0.5)))
    steps = sorted(cm._finalized_steps())
    assert steps == [4, 5]  # keep=2
    assert cm.latest_step() == 5
    assert (tmp_path / "latest").read_text().strip() == "step_5"


def test_async_save_publishes(tmp_path):
    model = _mlp(seed=4)
    cm = CheckpointManager(directory=str(tmp_path), model=model,
                           interval=0, async_save=True)
    cm.save()
    cm._join_save()
    assert cm.latest_step() == 0
    cm2 = CheckpointManager(directory=str(tmp_path), model=_mlp(seed=5),
                            interval=0)
    assert cm2.load_latest() == 0


def test_sigterm_flushes_final_checkpoint(tmp_path):
    model = _mlp(seed=6)
    cm = CheckpointManager(directory=str(tmp_path), model=model,
                           interval=0, async_save=False)
    caught = []
    prev = signal.signal(signal.SIGTERM, lambda *a: caught.append(a))
    try:
        assert cm.install_preemption_handler()
        cm._step = 7
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.1)
        assert caught  # chained to the pre-existing handler
        assert cm.latest_step() == 7  # final flush published
    finally:
        cm.close()
        signal.signal(signal.SIGTERM, prev)


def test_kill9_mid_save_previous_checkpoint_loadable(tmp_path):
    """The atomicity drill: a writer hard-killed mid-save (chaos save:crash
    = os._exit inside the data write) must leave the previous checkpoint
    fully loadable and the directory GC-able."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "multiproc", "ckpt_crash_worker.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, worker, str(tmp_path)],
        capture_output=True, text=True, timeout=180, cwd=repo, env=env)
    assert "FIRST_SAVED 0" in proc.stdout, proc.stderr
    assert proc.returncode == 137, (proc.returncode, proc.stderr)
    assert "UNREACHABLE" not in proc.stdout

    model = _mlp(seed=0)
    cm = CheckpointManager(directory=str(tmp_path), model=model,
                           interval=0, async_save=False)
    assert cm.latest_step() == 0  # the crashed step-1 save never published
    assert cm.load_latest() == 0  # and the survivor passes CRC verification


# ---------------------------------------------------------------------------
# Atomic + CRC-verified IO (paddle.save / distributed.checkpoint)
# ---------------------------------------------------------------------------

def test_paddle_save_roundtrip_with_crc(tmp_path):
    path = str(tmp_path / "model.pdparams")
    obj = {"w": paddle.to_tensor(np.arange(6, dtype=np.float32)),
           "meta": {"epoch": 3}}
    paddle.save(obj, path)
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    loaded = paddle.load(path)
    np.testing.assert_allclose(loaded["w"].numpy(), np.arange(6))
    assert loaded["meta"]["epoch"] == 3


def test_paddle_load_detects_corruption(tmp_path):
    path = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.ones([8])}, path)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    with pytest.raises(DataLossError, match="CRC mismatch"):
        paddle.load(path)


def test_paddle_load_detects_truncation(tmp_path):
    path = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.ones([128])}, path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(DataLossError):
        paddle.load(path)


def test_paddle_load_pre_crc_files_still_load(tmp_path):
    """Files written by older builds (no CRC footer) stay loadable."""
    path = str(tmp_path / "old.pdparams")
    with open(path, "wb") as f:
        pickle.dump({"x": 1}, f, protocol=4)
    assert paddle.load(path) == {"x": 1}


def test_dist_checkpoint_corruption_fails_loudly(tmp_path):
    dckpt.save_state_dict({"w": paddle.ones([16])}, str(tmp_path))
    data_file = next(f for f in os.listdir(tmp_path)
                     if f.endswith(".distcp"))
    p = tmp_path / data_file
    raw = bytearray(p.read_bytes())
    raw[3] ^= 0xFF
    p.write_bytes(bytes(raw))
    with pytest.raises(DataLossError, match="CRC mismatch"):
        dckpt.load_state_dict({"w": paddle.zeros([16])}, str(tmp_path))


def test_dist_checkpoint_truncated_metadata_fails_loudly(tmp_path):
    dckpt.save_state_dict({"w": paddle.ones([4])}, str(tmp_path))
    meta_file = next(f for f in os.listdir(tmp_path)
                     if f.endswith(".metadata"))
    p = tmp_path / meta_file
    p.write_bytes(p.read_bytes()[:10])
    with pytest.raises(DataLossError, match="metadata"):
        dckpt.load_state_dict({"w": paddle.zeros([4])}, str(tmp_path))


@pytest.mark.parametrize("save_ranks,load_ranks", [
    ([0, 1, 2, 3], [0, 1]),        # shrink: survivors after a rank loss
    ([0, 1], [0, 1, 2, 3]),        # grow: rejoined ranks widen the mesh
    ([0, 1, 2, 3], [0, 1, 2, 3, 4, 5, 6, 7]),  # grow past launch world
], ids=["shrink-4to2", "grow-2to4", "grow-4to8"])
def test_reshard_on_load_after_world_change(tmp_path, save_ranks,
                                            load_ranks):
    """A checkpoint written under one sharding loads into a differently
    sized mesh — the reshard-on-load path used after losing ranks
    (shrink) or re-admitting them (grow), CRC verified along the way."""
    save_mesh = dist.ProcessMesh(save_ranks, dim_names=["mp"])
    w = paddle.to_tensor(
        np.arange(64, dtype=np.float32).reshape(16, 4))
    ref = w.numpy().copy()
    sharded = dist.shard_tensor(w, save_mesh, [dist.Shard(0)])
    dckpt.save_state_dict({"w": sharded}, str(tmp_path))

    load_mesh = dist.ProcessMesh(load_ranks, dim_names=["mp"])
    target = dist.shard_tensor(paddle.zeros([16, 4]), load_mesh,
                               [dist.Shard(0)])
    sd = {"w": target}
    dckpt.load_state_dict(sd, str(tmp_path))
    np.testing.assert_allclose(np.asarray(sd["w"]._data), ref)
    assert not sd["w"]._data.sharding.is_fully_replicated


@pytest.mark.parametrize("from_pp,to_pp,from_dp,to_dp", [
    (4, 2, 2, 1),   # simultaneous shrink on both axes: 4x2 -> 2x1
    (2, 4, 1, 2),   # the inverse 3D move (grow both axes back)
    (4, 1, 4, 2),   # collapse the pipeline while halving dp
], ids=["shrink-4x2-to-2x1", "grow-2x1-to-4x2", "collapse-4x4-to-1x2"])
def test_reshard_pp_with_simultaneous_dp_shrink_bit_exact(
        from_pp, to_pp, from_dp, to_dp):
    """A 3D world change loses ranks on BOTH axes at once: the pipeline
    degree shrinks (reshard_pp restacks the blocks) while the dp degree
    shrinks (each per-stage ZeRO-1 flat accumulator regroups its dp-shard
    axis). Both moves are pure reshapes over a fixed flat layer order, so
    the composed round trip must be bitwise — including the optimizer
    moments riding in the blocks subtree."""
    L, S = 8, 12                       # layers; flat-shard elems per dp rank
    flat = from_dp * S                 # per-layer flat accumulator length
    assert flat % to_dp == 0
    lps = L // from_pp

    def leaf(tag, *shape):
        n = int(np.prod(shape))
        return (np.arange(n, dtype=np.float32) + 1000.0 * tag).reshape(shape)

    state = {
        "embed": leaf(1, 32, 16),      # pp-invariant, passes through
        "blocks": {
            "w": leaf(2, from_pp, lps, 16, 16),
            "b": leaf(3, from_pp, lps, 16),
            # per-stage ZeRO-1 flat Adam moment, sharded over dp ranks
            "w.acc.m": leaf(4, from_pp, lps, from_dp, S),
        },
    }
    ref = {k: v.copy() for k, v in state["blocks"].items()}

    # pp axis: restack stages
    out = CheckpointManager.reshard_pp(state, to_pp)
    assert out["blocks"]["w"].shape == (to_pp, L // to_pp, 16, 16)
    np.testing.assert_array_equal(np.asarray(out["embed"]), state["embed"])

    # dp axis: regroup each layer's flat shard axis [from_dp, S] ->
    # [to_dp, flat/to_dp] without touching the flat element order
    acc = np.asarray(out["blocks"]["w.acc.m"])
    out["blocks"]["w.acc.m"] = acc.reshape(
        to_pp, L // to_pp, to_dp, flat // to_dp)

    # flat layer order is the invariant both moves preserve
    for k in ref:
        np.testing.assert_array_equal(
            np.asarray(out["blocks"][k]).reshape(L, -1),
            ref[k].reshape(L, -1))

    # compose the inverse moves: bitwise round trip on both axes
    back = CheckpointManager.reshard_pp(out, from_pp)
    back["blocks"]["w.acc.m"] = np.asarray(
        back["blocks"]["w.acc.m"]).reshape(from_pp, lps, from_dp, S)
    for k in ref:
        got = np.asarray(back["blocks"][k])
        assert got.dtype == ref[k].dtype and got.shape == ref[k].shape
        np.testing.assert_array_equal(got, ref[k])


# ---------------------------------------------------------------------------
# Distress path exception-proofing
# ---------------------------------------------------------------------------

def test_distress_dump_never_raises_and_warns(monkeypatch, tmp_path, capfd):
    from paddle_tpu.observability import distress

    def boom(*a, **k):
        raise RuntimeError("serializer exploded")

    monkeypatch.setattr(distress.json, "dump", boom)
    path = distress.dump("unit_test", directory=str(tmp_path))
    assert path == ""  # swallowed, not raised
    assert "distress dump failed" in capfd.readouterr().err
    assert not list(tmp_path.iterdir())  # no half-written artifact


def test_distress_dump_section_failure_degrades_gracefully(tmp_path):
    from paddle_tpu.observability import distress

    rec = observability.recorder()
    orig = rec.to_chrome_trace
    rec.to_chrome_trace = lambda: (_ for _ in ()).throw(ValueError("nope"))
    try:
        path = distress.dump("unit_test_sections", directory=str(tmp_path))
    finally:
        rec.to_chrome_trace = orig
    assert path
    doc = json.loads(open(path).read())
    assert "unserializable" in doc["chrome_trace"]
    assert isinstance(doc["metrics"], dict)  # other sections intact


def test_watchdog_report_survives_dump_failure(no_abort, capfd, monkeypatch):
    """The original timeout report must print even when the distress dump
    machinery is completely broken."""
    monkeypatch.setattr(observability, "dump_distress",
                        lambda *a, **k: (_ for _ in ()).throw(
                            RuntimeError("dump broken")))
    flags.set_flags({"watchdog_policy": "", "comm_watchdog_abort": False})
    mgr = cw.CommTaskManager()
    _expire_once(mgr, timeout=0.25, deadline=1.0)
    err = capfd.readouterr().err
    assert "COLLECTIVE TIMEOUT" in err
    assert "op=all_reduce" in err

"""Block-scaled int8 quantized collectives (distributed/quant_comm.py).

Covers the int8 wire end to end on the 8-virtual-device CPU mesh:

- block codec round-trip and the scale edge cases (all-zero bucket,
  single outlier, pad tail) with float32 scales riding in the wire
- error feedback: the residual drains to zero on constant grads and the
  delivered sum telescopes to the true gradient sum
- `no_sync` k-step accumulation is bit-exact vs quantizing the
  accumulated total once
- the 13-optimizer sharded-update parity matrix at int8 tolerance, on
  2/4/8-rank groups
- the chaos hang drill names the quantized collective (`q8_gather`)
- pipeline pp=2 loss parity with quantized stage handoffs
"""
import os
from collections import OrderedDict

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import observability as obs
from paddle_tpu.core import flags
from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.distributed import parallel as par
from paddle_tpu.distributed import quant_comm as qc


@pytest.fixture(scope="module", autouse=True)
def _env():
    os.environ["PADDLE_TRAINERS_NUM"] = "8"
    dist.collective.destroy_process_group()
    dist.init_parallel_env()
    yield
    os.environ.pop("PADDLE_TRAINERS_NUM", None)
    dist.collective.destroy_process_group()


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    flags.set_flags({"dp_overlap": True, "dp_shard_update": False,
                     "dp_grad_comm_dtype": "", "dp_comm_block_size": 256,
                     "pp_p2p_comm_dtype": "", "chaos_spec": "",
                     "comm_timeout": 0.0, "watchdog_policy": "",
                     "comm_watchdog_abort": False})


def _metric(name, labels=None):
    return obs.registry().value(name, labels or {})


class _MLP(nn.Layer):
    def __init__(self, din=8, dhid=16, dout=4):
        super().__init__()
        self.l1 = nn.Linear(din, dhid)
        self.l2 = nn.Linear(dhid, dout)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return self.l2(F.relu(self.l1(x)))


def _train(opt_cls, shard, steps=2, group=None, seed=7):
    flags.set_flags({"dp_shard_update": shard})
    paddle.seed(seed)
    m = _MLP()
    d = dist.DataParallel(m, group=group or dist.get_group(0))
    o = opt_cls(learning_rate=0.05, parameters=m.parameters())
    so = dist.sharded_update(o, d) if shard else o
    for i in range(steps):
        x = paddle.to_tensor(
            np.random.RandomState(i).randn(8, 8).astype(np.float32))
        d(x).mean().backward()
        so.step()
        so.clear_grad()
    flags.set_flags({"dp_shard_update": False})
    return [np.asarray(p._data) for p in m.parameters()], so, d


def _params(*shapes, seed=0, scale=1.0):
    rs = np.random.RandomState(seed)
    return [Parameter.from_tensor(
        Tensor(jnp.asarray((rs.randn(*s) * scale).astype(np.float32))),
        name=f"_qc_p{i}") for i, s in enumerate(shapes)]


# ---------------------------------------------------------------------------
# Block codec: round-trip + scale edge cases
# ---------------------------------------------------------------------------

class TestBlockCodec:
    def test_wire_layout(self):
        assert qc.wire_layout(256, 256) == (256, 1, 260)
        assert qc.wire_layout(257, 256) == (512, 2, 520)
        assert qc.wire_layout(0, 256) == (256, 1, 260)

    def test_roundtrip_within_block_error_bound(self):
        block = 64
        flat = jnp.asarray(
            (np.random.RandomState(3).randn(4 * block) * 5)
            .astype(np.float32))
        wire, residual = qc.encode_flat(flat, block)
        assert wire.dtype == jnp.int8
        assert wire.shape == (4 * block + 4 * 4,)
        out = qc.decode_flat(wire, 4, block)
        absmax = np.abs(np.asarray(flat)).reshape(4, block).max(axis=1)
        bound = np.repeat(absmax / 254 + 1e-7, block)
        err = np.abs(np.asarray(out) - np.asarray(flat))
        assert np.all(err <= bound)
        # the residual is exactly the round-trip error
        assert np.allclose(np.asarray(residual), np.asarray(flat - out),
                           atol=1e-6)

    def test_all_zero_bucket_is_exact(self):
        flat = jnp.zeros((128,), jnp.float32)
        wire, residual = qc.encode_flat(flat, 128)
        out = qc.decode_flat(wire, 1, 128)
        assert np.array_equal(np.asarray(out), np.zeros(128, np.float32))
        assert np.array_equal(np.asarray(residual),
                              np.zeros(128, np.float32))

    def test_single_outlier_block(self):
        # f32 scales: an outlier that would overflow an f16 scale
        # (absmax * 127 > 65504) must round-trip cleanly, and the other
        # elements of its block quantize to exact zeros
        flat = np.zeros(256, np.float32)
        flat[17] = 1e4
        wire, _ = qc.encode_flat(jnp.asarray(flat), 256)
        out = np.asarray(qc.decode_flat(wire, 1, 256))
        assert abs(out[17] - 1e4) / 1e4 < 1e-5
        assert np.array_equal(np.delete(out, 17),
                              np.zeros(255, np.float32))

    def test_tiny_values_keep_nonzero_scale(self):
        # f16 scale storage would flush absmax/127 ~ 8e-9 to zero and
        # deliver nothing forever; f32 scales must keep quantizing
        flat = jnp.full((64,), 1e-6, jnp.float32)
        wire, residual = qc.encode_flat(flat, 64)
        out = np.asarray(qc.decode_flat(wire, 1, 64))
        assert np.all(out > 0)
        assert np.max(np.abs(out - 1e-6)) <= 1e-6 / 254 + 1e-12

    def test_pad_tail_through_bucket_executables(self):
        flags.set_flags({"dp_comm_block_size": 16})
        ps = _params((7, 3), (5,), seed=5)  # numel 26 -> 2 blocks of 16
        b = par._Bucket(0, ps, nranks=1, comm_dtype="int8")
        assert (b.qpadded, b.qblocks) == (32, 2)
        assert b.nbytes == 32 + 4 * 2
        qpack = qc.make_pack_q8(b)
        qdecode = qc.make_decode_q8(b)
        wire, _ = qpack([p._data for p in ps], qc.zeros_residual(b))
        out = np.asarray(qdecode(jnp.stack([wire])))
        flat = np.concatenate(
            [np.asarray(p._data).ravel() for p in ps])
        assert out.shape == (26,)  # pad tail sliced off
        assert np.max(np.abs(out - flat)) <= np.abs(flat).max() / 254 + 1e-7

    def test_bucket_wire_bytes_accounting(self):
        ps = _params((64, 64), seed=1)
        b8 = par._Bucket(0, ps, nranks=8, comm_dtype="int8")
        qpadded, nblocks, wire = qc.wire_layout(b8.padded, b8.qblock)
        assert b8.nbytes == wire == qpadded + 4 * nblocks
        bf = par._Bucket(0, ps, nranks=8, comm_dtype="bfloat16")
        assert bf.nbytes == bf.padded * 2  # non-int8 unchanged

    def test_bad_block_size_rejected(self):
        flags.set_flags({"dp_comm_block_size": 0})
        with pytest.raises(ValueError, match="dp_comm_block_size"):
            qc.block_size()

    def test_block_size_keys_the_plan(self):
        ps = _params((16, 16), (16,), seed=2)
        cache = OrderedDict()
        flags.set_flags({"dp_comm_block_size": 256})
        p1 = par._build_plan(ps, None, 25, 1, "int8", cache=cache)
        flags.set_flags({"dp_comm_block_size": 64})
        p2 = par._build_plan(ps, None, 25, 1, "int8", cache=cache)
        assert p1 is not p2
        assert (p1.buckets[0].qblock, p2.buckets[0].qblock) == (256, 64)
        flags.set_flags({"dp_comm_block_size": 256})
        assert par._build_plan(ps, None, 25, 1, "int8", cache=cache) is p1


# ---------------------------------------------------------------------------
# Error feedback
# ---------------------------------------------------------------------------

class TestErrorFeedback:
    def test_residual_drains_to_zero_on_constant_grads(self):
        # c = 127 makes scale = 1.0, so dequant is exact and the
        # residual hits exactly zero from the first step on
        ps = _params((8, 16), seed=0)
        b = par._Bucket(0, ps, nranks=1, comm_dtype="int8")
        qpack = qc.make_pack_q8(b)
        grads = [jnp.full((8, 16), 127.0, jnp.float32)]
        residual = qc.zeros_residual(b)
        for _ in range(3):
            wire, residual = qpack(grads, residual)
            assert np.array_equal(np.asarray(residual),
                                  np.zeros(b.qpadded, np.float32))
            out = np.asarray(qc.decode_flat(wire, b.qblocks, b.qblock))
            assert np.array_equal(out[:b.numel],
                                  np.full(128, 127.0, np.float32))

    def test_delivered_sum_telescopes(self):
        # generic constant c: per-step delivery wobbles by <= scale/2 but
        # the error feedback telescopes — after T steps the summed
        # deliveries differ from T*c by at most the final residual
        ps = _params((8, 16), seed=0)
        b = par._Bucket(0, ps, nranks=1, comm_dtype="int8")
        qpack = qc.make_pack_q8(b)
        c, T = 0.3, 10
        grads = [jnp.full((8, 16), c, jnp.float32)]
        residual = qc.zeros_residual(b)
        delivered = np.zeros(b.numel, np.float32)
        for _ in range(T):
            wire, residual = qpack(grads, residual)
            delivered += np.asarray(
                qc.decode_flat(wire, b.qblocks, b.qblock))[:b.numel]
        scale_bound = (c + abs(c) / 254) / 127  # absmax <= c + residual
        assert np.max(np.abs(delivered - T * c)) <= scale_bound
        assert np.max(np.abs(np.asarray(residual))) <= scale_bound

    def test_no_sync_accumulation_bit_exact(self):
        """k no_sync steps + one synced backward must deliver exactly
        decode(encode(sum of grads)): the codec runs once on the
        accumulated total, never on the partial sums."""
        flags.set_flags({"dp_grad_comm_dtype": "int8"})
        g = dist.get_group(0)
        xs = [np.random.RandomState(40 + j).randn(8, 8).astype(np.float32)
              for j in range(3)]

        paddle.seed(23)
        m = _MLP()
        d = dist.DataParallel(m, group=g)
        with d.no_sync():
            for xa in xs[:-1]:
                d(paddle.to_tensor(xa)).mean().backward()
        d(paddle.to_tensor(xs[-1])).mean().backward()
        got = [np.asarray(p._grad) for p in m.parameters()]

        # twin model, same seed: accumulate the same grads with no DP
        paddle.seed(23)
        m2 = _MLP()
        for xa in xs:
            m2(paddle.to_tensor(xa)).mean().backward()
        by_pos = {id(p): i for i, p in enumerate(m.parameters())}
        totals = [p._grad for p in m2.parameters()]

        plan = d._reducer._ensure_plan()
        n = g.nranks
        for b in plan.buckets:
            arrs = [totals[by_pos[id(p)]] for p in b.params]
            wire, _ = b.qpack(arrs, qc.zeros_residual(b))
            flat = b.qdecode(jnp.stack([wire] * n))
            expect = b.unpack_grads(flat)
            for p, e in zip(b.params, expect):
                a = got[by_pos[id(p)]]
                assert np.array_equal(a, np.asarray(e)), (
                    f"bucket {b.index} param {p.name}: "
                    f"maxdiff {np.max(np.abs(a - np.asarray(e)))}")


# ---------------------------------------------------------------------------
# Sharded-update parity at int8 tolerance
# ---------------------------------------------------------------------------

# the same 13 optimizers as test_dp_overlap's fp32 matrix; with the int8
# wire both paths see identical decoded grads, so sharded must still be
# bit-exact vs replicated (Lamb via its documented replicated fallback)
PARITY_OPTIMIZERS = [opt.SGD, opt.Momentum, opt.Adam, opt.AdamW, opt.Adagrad,
                     opt.RMSProp, opt.Adadelta, opt.Adamax, opt.Lamb,
                     opt.ASGD, opt.NAdam, opt.RAdam, opt.Rprop]

INT8_TOL = 5e-2  # vs the fp32 wire (the bf16-wire test's tolerance)
# Adam normalizes per element by sqrt(v): quantization noise on
# near-zero grads can flip an element's direction outright, moving it a
# full lr per step either way — bound is 2 * steps * lr = 0.2
ADAM_TOL = 0.2


class TestInt8ShardedParity:
    @pytest.mark.parametrize(
        "opt_cls", PARITY_OPTIMIZERS, ids=lambda c: c.__name__)
    def test_sharded_bit_exact_vs_replicated(self, opt_cls, recwarn):
        flags.set_flags({"dp_grad_comm_dtype": "int8"})
        w_repl, _, _ = _train(opt_cls, shard=False)
        w_sh, _, _ = _train(opt_cls, shard=True)
        for i, (a, b) in enumerate(zip(w_repl, w_sh)):
            assert np.array_equal(a, b), (
                f"{opt_cls.__name__} param {i}: "
                f"maxdiff {np.max(np.abs(a - b))}")

    @pytest.mark.parametrize(
        "opt_cls", [opt.SGD, opt.Momentum, opt.Adam],
        ids=lambda c: c.__name__)
    def test_tracks_fp32_within_tolerance(self, opt_cls):
        w_ref, _, _ = _train(opt_cls, shard=False)
        flags.set_flags({"dp_grad_comm_dtype": "int8"})
        w_q, _, _ = _train(opt_cls, shard=True)
        tol = ADAM_TOL if opt_cls is opt.Adam else INT8_TOL
        for a, b in zip(w_ref, w_q):
            assert str(b.dtype) == "float32"
            assert np.allclose(a, b, atol=tol)

    @pytest.mark.parametrize("nranks", [2, 4, 8])
    def test_rank_groups(self, nranks):
        g = (dist.get_group(0) if nranks == 8
             else dist.new_group(list(range(nranks))))
        assert g.nranks == nranks
        w_ref, _, _ = _train(opt.Adam, shard=False, group=g)
        flags.set_flags({"dp_grad_comm_dtype": "int8"})
        w_repl, _, _ = _train(opt.Adam, shard=False, group=g)
        w_sh, _, _ = _train(opt.Adam, shard=True, group=g)
        for a, b, c in zip(w_ref, w_repl, w_sh):
            assert np.array_equal(b, c)  # sharded == replicated, int8
            assert np.allclose(a, c, atol=ADAM_TOL)  # tracks fp32

    def test_wire_bytes_accounted(self):
        before = _metric("paddle_dp_wire_bytes_total", {"dtype": "int8"})
        before_ref = _metric("paddle_dp_wire_bytes_ref_total")
        flags.set_flags({"dp_grad_comm_dtype": "int8"})
        steps = 2
        _, _, d = _train(opt.SGD, shard=False, steps=steps)
        plan = d._reducer._ensure_plan()
        wire = sum(b.nbytes for b in plan.buckets)
        ref = sum(b.padded * 4 for b in plan.buckets)
        assert (_metric("paddle_dp_wire_bytes_total", {"dtype": "int8"})
                == before + steps * wire)
        assert (_metric("paddle_dp_wire_bytes_ref_total")
                == before_ref + steps * ref)
        dp = obs.summary()["dp"]
        assert dp["wire_bytes_ref"] >= dp["wire_bytes"] > 0


# ---------------------------------------------------------------------------
# Chaos drill: the hang names the quantized collective
# ---------------------------------------------------------------------------

class TestChaosDrill:
    def test_watchdog_names_quantized_collective(self, capfd):
        flags.set_flags({"chaos_spec":
                         "collective:hang@op=q8_gather;delay=1.0",
                         "comm_timeout": 0.3,
                         "watchdog_policy": "warn",
                         "comm_watchdog_abort": False,
                         "dp_grad_comm_dtype": "int8"})
        before = _metric("paddle_watchdog_escalations_total",
                         {"stage": "warn"})
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m)
        o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
        d(paddle.to_tensor(
            np.ones((4, 8), np.float32))).mean().backward()
        o.step()
        assert _metric("paddle_watchdog_escalations_total",
                       {"stage": "warn"}) >= before + 1
        err = capfd.readouterr().err
        assert "stage=warn" in err
        assert "dp:q8_gather:bucket0" in err


# ---------------------------------------------------------------------------
# Pipeline: pp=2 with quantized stage handoffs
# ---------------------------------------------------------------------------

class TestQuantizedPipeline:
    def test_pp2_loss_parity_with_quantized_handoff(self):
        from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers \
            import pp_layers
        from paddle_tpu.distributed.pipeline import PipelineEngine

        M, DIN, DHID, DOUT = 4, 16, 32, 4

        def _mse(out, label):
            return ((out - label) ** 2).mean()

        rs = np.random.RandomState(1)
        x = paddle.to_tensor(rs.normal(size=(M, DIN)).astype(np.float32))
        y = paddle.to_tensor(rs.normal(size=(M, DOUT)).astype(np.float32))

        def train(pp, wire, steps=3):
            flags.set_flags({"pp_p2p_comm_dtype": wire})
            model = pp_layers.PipelineLayer(
                layers=[pp_layers.LayerDesc(nn.Linear, DIN, DHID),
                        pp_layers.LayerDesc(nn.ReLU),
                        pp_layers.LayerDesc(nn.Linear, DHID, DHID),
                        pp_layers.LayerDesc(nn.ReLU),
                        pp_layers.LayerDesc(nn.Linear, DHID, DOUT)],
                loss_fn=_mse, num_stages=pp)
            rs2 = np.random.RandomState(0)
            for p in model.parameters():
                p.set_value(paddle.to_tensor(
                    rs2.normal(scale=0.3, size=p.shape)
                    .astype(np.float32)))
            engine = PipelineEngine(model, accumulate_steps=M)
            o = opt.SGD(learning_rate=0.05,
                        parameters=model.parameters())
            losses = []
            for _ in range(steps):
                loss = engine.run(x, y, train=True)
                o.step()
                o.clear_grad()
                losses.append(float(np.asarray(loss._data)))
            flags.set_flags({"pp_p2p_comm_dtype": ""})
            return losses

        ref = train(1, "")
        before = _metric("paddle_pp_wire_bytes_total", {"dtype": "int8"})
        q = train(2, "int8")
        err = max(abs(a - b) for a, b in zip(ref, q))
        assert err <= 0.1, f"quantized pp losses {q} vs {ref}"
        assert q[-1] < q[0]  # still trains
        assert _metric("paddle_pp_wire_bytes_total",
                       {"dtype": "int8"}) > before

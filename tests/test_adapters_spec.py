"""Multi-tenant adapter serving + speculative decoding tests.

Two contracts pin the whole subsystem:

- **bit-exactness** — LoRA adapters change ONLY the rows that asked for
  them (base rows in a mixed batch match the adapter-off engine
  token-for-token; each adapter row matches a solo run of that
  adapter), and speculative decoding changes NOTHING (greedy spec
  output is identical to plain greedy decode, through preemption
  recompute, prefix/COW sharing, chaos eviction and replica failover —
  a wrong draft costs acceptance rate, never correctness);
- **zero steady-state retraces** — which adapter a request uses is
  data (slot selectors into the stacked rank-class pack), so hot-swaps
  and chaos evictions never build a new step executable; the draft
  holds at exactly two cached executables of its own.

Also covers: the CRC'd versioned adapter manifest, the raw/q8 wire
codec, pin/unpin refcount pairing, LRU slot eviction +
NoAdapterSlotsError, the transport publish/fetch plane under chaos
``adapter:corrupt``/``adapter:delay``, adapter-aware router placement
with transport prefetch, the per-adapter fleet digest, and the
``summary()["adapters"]``/``["spec"]`` observability sections.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.inference.serving import (AdapterCorruptError,
                                          AdapterManager,
                                          AdapterMissingError,
                                          AdapterTransport, DraftModel,
                                          LoraAdapter, NoAdapterSlotsError,
                                          PagedServingEngine, ServingRouter,
                                          load_adapter, make_adapter,
                                          pack_adapter, save_adapter,
                                          unpack_adapter)
from paddle_tpu.inference.serving.adapters import rank_class, target_dims
from paddle_tpu.models import llama as L

ENGINE_KW = dict(num_blocks=96, block_size=8, max_batch=8, token_budget=32)


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                        intermediate_size=64, num_layers=2, num_heads=4,
                        num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def draft(tiny):
    """Half-depth draft reusing the target's own layer-prefix weights —
    cheap, and correlated enough that acceptance is well above zero."""
    cfg, params = tiny
    dcfg = L.LlamaConfig(vocab_size=97, hidden_size=32,
                         intermediate_size=64, num_layers=1, num_heads=4,
                         num_kv_heads=2, max_seq_len=96, dtype=jnp.float32)
    dparams = {"embed": params["embed"],
               "final_norm": params["final_norm"],
               "lm_head": params["lm_head"],
               "blocks": jax.tree.map(lambda a: a[:1], params["blocks"])}
    return dcfg, dparams


def _prompts(cfg, n, ln=8, seed=1):
    rs = np.random.RandomState(seed)
    return [rs.randint(1, cfg.vocab_size, (ln,)).tolist() for _ in range(n)]


def _run(eng, prompts, adapters=None, max_new=8, **kw):
    rids = []
    for i, p in enumerate(prompts):
        extra = dict(kw)
        if adapters is not None and adapters[i] is not None:
            extra["adapter"] = adapters[i]
        rids.append(eng.submit(p, max_new_tokens=max_new, **extra))
    done = {c.rid: c.output_tokens for c in eng.run()}
    return [done.get(r) for r in rids]


def _engine(tiny, **over):
    cfg, params = tiny
    kw = dict(ENGINE_KW, **over)
    return PagedServingEngine(cfg, params, max_len=cfg.max_seq_len, **kw)


def _spec_engine(tiny, draft, **over):
    dcfg, dparams = draft
    return _engine(tiny, draft=DraftModel(dcfg, dparams), spec_k=3, **over)


# ---------------------------------------------------------------------------
# manifest: CRC'd versioned persistence
# ---------------------------------------------------------------------------

class TestManifest:
    def test_round_trip_bit_exact(self, tiny, tmp_path):
        cfg, _ = tiny
        ad = make_adapter(cfg, "billing", rank=3, alpha=6.0, seed=7)
        p = str(tmp_path / "billing.json")
        save_adapter(ad, cfg, p)
        got = load_adapter(p, cfg)
        assert got.name == "billing" and got.rank == 3
        assert got.alpha == 6.0 and got.scaling == 2.0
        for t in ad.weights:
            np.testing.assert_array_equal(got.weights[t][0],
                                          ad.weights[t][0])
            np.testing.assert_array_equal(got.weights[t][1],
                                          ad.weights[t][1])

    def test_hand_edit_fails_crc(self, tiny, tmp_path):
        cfg, _ = tiny
        p = str(tmp_path / "a.json")
        save_adapter(make_adapter(cfg, "a"), cfg, p)
        with open(p) as f:
            doc = json.load(f)
        doc["payload"]["alpha"] = 99.0
        with open(p, "w") as f:
            json.dump(doc, f)
        with pytest.raises(ValueError, match="CRC"):
            load_adapter(p)

    def test_bad_format_and_version_fail_loud(self, tiny, tmp_path):
        cfg, _ = tiny
        p = str(tmp_path / "a.json")
        save_adapter(make_adapter(cfg, "a"), cfg, p)
        with open(p) as f:
            doc = json.load(f)
        for key, val, pat in (("format", "nope", "format"),
                              ("version", 99, "version")):
            bad = dict(doc)
            bad[key] = val
            with open(p, "w") as f:
                json.dump(bad, f)
            with pytest.raises(ValueError, match=pat):
                load_adapter(p)
        with open(p, "w") as f:
            f.write("{not json")
        with pytest.raises(ValueError, match="unreadable"):
            load_adapter(p)

    def test_model_signature_mismatch(self, tiny, tmp_path):
        cfg, _ = tiny
        p = str(tmp_path / "a.json")
        save_adapter(make_adapter(cfg, "a"), cfg, p)
        other = L.LlamaConfig(vocab_size=97, hidden_size=32,
                              intermediate_size=64, num_layers=3,
                              num_heads=4, num_kv_heads=2, max_seq_len=96,
                              dtype=jnp.float32)
        with pytest.raises(ValueError, match="different model"):
            load_adapter(p, other)


# ---------------------------------------------------------------------------
# wire codec: raw + q8
# ---------------------------------------------------------------------------

class TestWireCodec:
    def test_raw_round_trip_bit_exact(self, tiny):
        cfg, _ = tiny
        ad = make_adapter(cfg, "w", rank=4, seed=2)
        got = unpack_adapter(pack_adapter(ad, wire="raw"))
        assert got.name == ad.name and got.rank == ad.rank
        for t in ad.weights:
            np.testing.assert_array_equal(got.weights[t][0],
                                          ad.weights[t][0])

    def test_int8_wire_smaller_and_close(self, tiny):
        cfg, _ = tiny
        ad = make_adapter(cfg, "w", rank=4, seed=2)
        raw, q8 = pack_adapter(ad, wire="raw"), pack_adapter(ad,
                                                             wire="int8")
        assert len(q8) < 0.5 * len(raw)
        got = unpack_adapter(q8)
        for t in ad.weights:
            a, b = ad.weights[t]
            np.testing.assert_allclose(got.weights[t][0], a, atol=2e-3)
            np.testing.assert_allclose(got.weights[t][1], b, atol=2e-3)

    def test_corrupt_blob_rejected(self, tiny):
        cfg, _ = tiny
        blob = pack_adapter(make_adapter(cfg, "w"), wire="raw")
        bad = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        with pytest.raises(AdapterCorruptError, match="CRC"):
            unpack_adapter(bad)
        with pytest.raises(AdapterCorruptError):
            unpack_adapter(b"garbage with no header newline?" * 3)

    def test_rank_class_padding(self):
        assert [rank_class(r) for r in (1, 2, 3, 4, 5, 8, 9)] == \
            [1, 2, 4, 4, 8, 8, 16]


# ---------------------------------------------------------------------------
# AdapterManager: slots, refcounts, LRU
# ---------------------------------------------------------------------------

class TestAdapterManager:
    def test_register_get_missing(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=2)
        mgr.register(make_adapter(cfg, "a"))
        assert mgr.registered("a") and mgr.names() == ["a"]
        assert not mgr.has("a")          # registered != device-resident
        with pytest.raises(AdapterMissingError):
            mgr.get("nope")
        with pytest.raises(AdapterMissingError):
            mgr.slot_of("a")             # not loaded yet

    def test_pin_unpin_refcount_pairing(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=2)
        mgr.register(make_adapter(cfg, "a"))
        with pytest.raises(AdapterMissingError):
            mgr.pin("ghost")             # raises BEFORE any count moves
        assert mgr.ref_count("ghost") == 0
        mgr.pin("a")
        mgr.pin("a")
        assert mgr.ref_count("a") == 2
        mgr.unpin("a")
        mgr.unpin("a")
        with pytest.raises(ValueError, match="unpin"):
            mgr.unpin("a")
        assert mgr.stats["pins"] == mgr.stats["unpins"] == 2

    def test_lru_eviction_counts_swap(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=1)
        for n in ("a", "b"):
            mgr.register(make_adapter(cfg, n, rank=4))
        mgr.ensure_loaded("a")
        assert mgr.has("a") and mgr.stats["swaps"] == 0
        mgr.ensure_loaded("b")           # evicts a (LRU, refcount 0)
        assert mgr.has("b") and not mgr.has("a")
        assert mgr.stats["evictions"] == 1
        mgr.ensure_loaded("a")           # re-load after eviction = swap
        assert mgr.stats["swaps"] == 1

    def test_all_slots_pinned_raises(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=1)
        for n in ("a", "b"):
            mgr.register(make_adapter(cfg, n, rank=4))
        mgr.pin("a")
        mgr.ensure_loaded("a")
        with pytest.raises(NoAdapterSlotsError, match="pinned"):
            mgr.ensure_loaded("b")
        mgr.unpin("a")                   # refcount 0 -> evictable again
        assert mgr.ensure_loaded("b")[0] == 4

    def test_evict_keeps_host_copy(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=2)
        mgr.register(make_adapter(cfg, "a"))
        cls, slot = mgr.ensure_loaded("a")
        before = np.asarray(mgr.device_packs(cls)["wq"][0][:, slot])
        assert mgr.evict_device("a", why="chaos")
        assert not mgr.has("a") and mgr.registered("a")
        assert not mgr.evict_device("a")        # idempotent
        cls2, slot2 = mgr.ensure_loaded("a")    # bit-identical re-pin
        after = np.asarray(mgr.device_packs(cls2)["wq"][0][:, slot2])
        np.testing.assert_array_equal(before, after)

    def test_replace_pinned_refused(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=2)
        mgr.register(make_adapter(cfg, "a", seed=1))
        mgr.pin("a")
        with pytest.raises(ValueError, match="pinned"):
            mgr.register(make_adapter(cfg, "a", seed=2))
        mgr.unpin("a")
        mgr.register(make_adapter(cfg, "a", seed=2))   # drain -> ok

    def test_bytes_accounting_and_snapshot(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=2)
        assert mgr.bytes_total() == mgr.bytes_in_use() == 0
        mgr.register(make_adapter(cfg, "a", rank=4))
        mgr.ensure_loaded("a")
        # slots are pre-allocated per class: total covers BOTH slots,
        # in_use only the occupied one
        assert mgr.bytes_total() == 2 * mgr.bytes_in_use() > 0
        dims = target_dims(cfg)
        want = sum(4 * cfg.num_layers * (din * 4 + 4 * dout)
                   for din, dout in dims.values())
        assert mgr.bytes_in_use() == want
        snap = mgr.snapshot()
        assert snap["registered"] == ["a"] and "a" in snap["resident"]
        assert snap["resident"]["a"]["rank_class"] == 4
        assert snap["slots_per_class"] == 2

    def test_mixed_rank_classes_separate_packs(self, tiny):
        cfg, _ = tiny
        mgr = AdapterManager(cfg, slots=1)
        mgr.register(make_adapter(cfg, "small", rank=2))
        mgr.register(make_adapter(cfg, "big", rank=8))
        c1, _ = mgr.ensure_loaded("small")
        c2, _ = mgr.ensure_loaded("big")
        assert (c1, c2) == (2, 8)
        # one slot per CLASS: different classes never evict each other
        assert mgr.has("small") and mgr.has("big")
        assert mgr.num_resident() == 2


# ---------------------------------------------------------------------------
# transport: publish/fetch, prefetch, chaos corrupt + delay drills
# ---------------------------------------------------------------------------

class TestTransport:
    def test_publish_fetch_prefetch(self, tiny):
        cfg, _ = tiny
        tr = AdapterTransport()
        ad = make_adapter(cfg, "pub", rank=4, seed=5)
        nbytes = tr.publish(ad)
        assert nbytes > 0 and tr.stats["publishes"] == 1
        got = tr.fetch("pub")
        assert got is not None and got.name == "pub"
        assert tr.fetch("ghost") is None
        mgr = AdapterManager(cfg, slots=2)
        assert mgr.prefetch("pub", tr) == "ok"
        assert mgr.registered("pub")
        assert mgr.prefetch("pub", tr) == "registered"
        assert mgr.prefetch("ghost", tr) == "miss"

    def test_chaos_corrupt_drill(self, tiny):
        """adapter:corrupt on the fetch path flips a payload byte; the
        CRC rejects it and prefetch degrades to result='corrupt' instead
        of registering damaged weights."""
        cfg, _ = tiny
        tr = AdapterTransport()
        tr.publish(make_adapter(cfg, "pub", seed=5))
        mgr = AdapterManager(cfg, slots=2)
        chaos.reconfigure("adapter:corrupt@op=fetch")
        try:
            assert mgr.prefetch("pub", tr) == "corrupt"
        finally:
            chaos.reconfigure("")
        assert not mgr.registered("pub")
        assert mgr.prefetch("pub", tr) == "ok"   # clean retry succeeds

    def test_chaos_corrupt_on_publish(self, tiny):
        cfg, _ = tiny
        tr = AdapterTransport()
        chaos.reconfigure("adapter:corrupt@op=publish")
        try:
            tr.publish(make_adapter(cfg, "pub", seed=5))
        finally:
            chaos.reconfigure("")
        with pytest.raises(AdapterCorruptError):
            tr.fetch("pub")

    def test_chaos_delay_drill(self, tiny):
        """adapter:delay sleeps at the choke point — slow prefetch, not
        broken prefetch: the fetch still succeeds afterwards."""
        cfg, _ = tiny
        tr = AdapterTransport()
        tr.publish(make_adapter(cfg, "pub", seed=5))
        chaos.reconfigure("adapter:delay@op=fetch;delay=0.05")
        try:
            t0 = time.perf_counter()
            got = tr.fetch("pub")
            dt = time.perf_counter() - t0
        finally:
            chaos.reconfigure("")
        assert got is not None and got.name == "pub"
        assert dt >= 0.05


# ---------------------------------------------------------------------------
# engine: mixed-adapter batches, hot-swap, zero retraces, chaos evict
# ---------------------------------------------------------------------------

class TestEngineAdapters:
    def test_mixed_batch_base_rows_bit_exact(self, tiny):
        cfg, _ = tiny
        prompts = _prompts(cfg, 4)
        base_out = _run(_engine(tiny), prompts)
        eng = _engine(tiny, adapter_slots=2)
        eng.adapters.register(make_adapter(cfg, "t-a", rank=4, alpha=8.0,
                                           seed=3, scale=0.3))
        mixed = _run(eng, prompts, adapters=["t-a", None, "t-a", None])
        assert mixed[1] == base_out[1] and mixed[3] == base_out[3]
        assert mixed[0] != base_out[0] and mixed[2] != base_out[2]

    def test_mixed_batch_matches_solo_runs(self, tiny):
        """Segmented application: each adapter row in a 2-adapter mixed
        batch is bit-identical to a solo run of that adapter."""
        cfg, _ = tiny
        prompts = _prompts(cfg, 4)
        ads = {n: make_adapter(cfg, n, rank=4, alpha=8.0, seed=s,
                               scale=0.3)
               for n, s in (("t-a", 3), ("t-b", 4))}

        def fresh():
            eng = _engine(tiny, adapter_slots=2)
            for a in ads.values():
                eng.adapters.register(a)
            return eng

        solo_a = _run(fresh(), prompts, adapters=["t-a"] * 4)
        solo_b = _run(fresh(), prompts, adapters=["t-b"] * 4)
        mixed = _run(fresh(), prompts,
                     adapters=["t-a", "t-b", "t-a", "t-b"])
        assert mixed == [solo_a[0], solo_b[1], solo_a[2], solo_b[3]]

    def test_hot_swap_beyond_slots_zero_retrace(self, tiny):
        """Three tenants over ONE device slot: every request forces an
        LRU swap, and none of it builds a new executable — adapter
        routing is data, not a trace key."""
        cfg, _ = tiny
        eng = _engine(tiny, adapter_slots=1)
        names = ["t-a", "t-b", "t-c"]
        for i, n in enumerate(names):
            eng.adapters.register(make_adapter(cfg, n, rank=4, seed=i))
        prompts = _prompts(cfg, 3)
        for n in names:                       # warm: serial, 1 slot
            _run(eng, prompts[:1], adapters=[n])
        builds = eng.stats["step_builds"]
        swaps0 = eng.adapters.stats["swaps"]
        for n in reversed(names):
            _run(eng, prompts[:1], adapters=[n])
        assert eng.stats["step_builds"] == builds
        assert eng.adapters.stats["swaps"] > swaps0

    def test_submit_unknown_adapter_fails_clean(self, tiny):
        eng = _engine(tiny)
        with pytest.raises(AdapterMissingError):
            eng.submit([1, 2, 3], max_new_tokens=4, adapter="ghost")
        assert eng.scheduler.queue_depth() == 0
        assert eng.adapters.stats["pins"] == eng.adapters.stats["unpins"]

    def test_completion_unpins_adapter(self, tiny):
        cfg, _ = tiny
        eng = _engine(tiny, adapter_slots=2)
        eng.adapters.register(make_adapter(cfg, "t-a"))
        _run(eng, _prompts(cfg, 2), adapters=["t-a", "t-a"])
        assert eng.adapters.ref_count("t-a") == 0
        assert eng.adapters.stats["pins"] == eng.adapters.stats["unpins"] \
            == 2

    def test_chaos_evict_mid_stream_bit_exact(self, tiny):
        """adapter:evict fires at the per-tick residency check: the slot
        is force-dropped mid-stream, the next tick reloads it (a swap),
        and the output stream never notices."""
        cfg, _ = tiny
        prompts = _prompts(cfg, 2)

        def fresh():
            eng = _engine(tiny, adapter_slots=2)
            eng.adapters.register(make_adapter(cfg, "t-a", rank=4,
                                               seed=3, scale=0.3))
            return eng

        ref = _run(fresh(), prompts, adapters=["t-a", "t-a"])
        eng = fresh()
        chaos.reconfigure("adapter:evict@op=use;call=3")
        try:
            got = _run(eng, prompts, adapters=["t-a", "t-a"])
        finally:
            chaos.reconfigure("")
        assert got == ref
        assert eng.adapters.stats["evictions"] >= 1
        assert eng.adapters.stats["swaps"] >= 1

    def test_adapter_bytes_ride_block_manager_gauges(self, tiny):
        cfg, _ = tiny
        eng = _engine(tiny, adapter_slots=2)
        kv_only = eng.blocks.bytes_total()
        eng.adapters.register(make_adapter(cfg, "t-a"))
        _run(eng, _prompts(cfg, 1), adapters=["t-a"])
        assert eng.blocks.bytes_total() == \
            kv_only + eng.adapters.bytes_total()
        assert eng.blocks.bytes_in_use() >= eng.adapters.bytes_in_use() > 0
        st = eng.engine_stats
        assert st["adapters_resident"] == 1
        assert st["adapter_bytes_in_use"] == eng.adapters.bytes_in_use()


# ---------------------------------------------------------------------------
# speculative decoding: bit-exact greedy parity in every regime
# ---------------------------------------------------------------------------

class TestSpeculative:
    def test_greedy_parity_weak_draft(self, tiny, draft):
        """A half-depth draft is WRONG often — and the output stream
        must not show it: bit-exact vs plain greedy, acceptance in
        (0, 1)."""
        cfg, _ = tiny
        prompts = _prompts(cfg, 4)
        base_out = _run(_engine(tiny), prompts, max_new=10)
        eng = _spec_engine(tiny, draft)
        assert _run(eng, prompts, max_new=10) == base_out
        assert eng.stats["spec_ticks"] > 0
        assert 0.0 < eng.spec.acceptance_rate <= 1.0

    def test_perfect_draft_full_acceptance(self, tiny):
        """Draft == target: every proposal is accepted, every tick emits
        k+1 tokens, and parity is trivially bit-exact."""
        cfg, params = tiny
        prompts = _prompts(cfg, 2)
        base_out = _run(_engine(tiny), prompts, max_new=9)
        eng = _engine(tiny, draft=DraftModel(cfg, params), spec_k=3)
        assert _run(eng, prompts, max_new=9) == base_out
        assert eng.spec.acceptance_rate == 1.0

    def test_parity_with_eos(self, tiny, draft):
        cfg, _ = tiny
        prompts = _prompts(cfg, 2)
        probe = _run(_engine(tiny), prompts, max_new=8)
        eos = probe[0][3]        # a token the stream actually produces
        base = _run(_engine(tiny), prompts, max_new=8, eos_token_id=eos)
        spec = _spec_engine(tiny, draft)
        assert _run(spec, prompts, max_new=8, eos_token_id=eos) == base

    def test_parity_through_preemption_recompute(self, tiny, draft):
        """A starved block pool forces preemption mid-decode; the
        epoch-guarded draft catch-up keeps the stream bit-exact."""
        cfg, _ = tiny
        kw = dict(num_blocks=10, block_size=8, max_batch=8,
                  token_budget=32)
        prompts = _prompts(cfg, 6)
        base = _run(_engine(tiny, **kw), prompts, max_new=10)
        eng = _spec_engine(tiny, draft, **kw)
        assert _run(eng, prompts, max_new=10) == base
        assert eng.scheduler.stats["preemptions"] >= 1

    def test_parity_with_prefix_sharing(self, tiny, draft):
        """Shared-prefix prompts ride the prefix cache + COW; the draft
        mirrors page copies eagerly and parity holds."""
        cfg, _ = tiny
        rs = np.random.RandomState(3)
        shared = rs.randint(1, cfg.vocab_size, (16,)).tolist()
        prompts = [shared + rs.randint(1, cfg.vocab_size, (3,)).tolist()
                   for _ in range(4)]
        base = _run(_engine(tiny), prompts, max_new=8)
        eng = _spec_engine(tiny, draft)
        assert _run(eng, prompts, max_new=8) == base
        assert eng.blocks.stats["prefix_hit_tokens"] > 0

    def test_sampled_requests_not_speculated(self, tiny, draft):
        """Greedy verification needs temperature==0 — sampled requests
        decode the normal path, spec stays off for them."""
        cfg, _ = tiny
        eng = _spec_engine(tiny, draft)
        out = _run(eng, _prompts(cfg, 2), max_new=6, temperature=0.8,
                   seed=11)
        assert all(len(o) == 6 for o in out)
        assert eng.stats["spec_ticks"] == 0

    def test_zero_retrace_and_two_draft_fns(self, tiny, draft):
        cfg, _ = tiny
        prompts = _prompts(cfg, 3)
        eng = _spec_engine(tiny, draft)
        first = _run(eng, prompts, max_new=8)
        builds = eng.stats["step_builds"]
        assert _run(eng, prompts, max_new=8) == first
        assert eng.stats["step_builds"] == builds
        # catch-up chunk + 1-token proposal: exactly two executables
        assert len(eng.spec._fns) <= 2
        assert eng.spec.stats["draft_builds"] <= 2

    def test_acceptance_accounting(self, tiny, draft):
        cfg, _ = tiny
        eng = _spec_engine(tiny, draft)
        _run(eng, _prompts(cfg, 3), max_new=8)
        s = eng.spec.stats
        assert s["proposed"] >= s["accepted"] >= 0
        assert s["ticks"] == eng.stats["spec_ticks"] > 0
        assert s["bonus"] == s["ticks"]
        assert eng.spec.acceptance_rate == round(
            s["accepted"] / s["proposed"], 4)
        snap = eng.spec.snapshot()
        assert snap["acceptance_rate"] == eng.spec.acceptance_rate
        assert "tracked_sequences" in snap
        st = eng.engine_stats
        assert st["spec_acceptance_rate"] == eng.spec.acceptance_rate

    def test_draft_validation_fails_loud(self, tiny):
        cfg, params = tiny
        bad_vocab = L.LlamaConfig(vocab_size=101, hidden_size=32,
                                  intermediate_size=64, num_layers=1,
                                  num_heads=4, num_kv_heads=2,
                                  max_seq_len=96, dtype=jnp.float32)
        with pytest.raises(ValueError, match="vocab"):
            _engine(tiny, draft=DraftModel(
                bad_vocab, L.init_params(bad_vocab, jax.random.PRNGKey(1))))
        short = L.LlamaConfig(vocab_size=97, hidden_size=32,
                              intermediate_size=64, num_layers=1,
                              num_heads=4, num_kv_heads=2, max_seq_len=32,
                              dtype=jnp.float32)
        with pytest.raises(ValueError, match="max_seq_len"):
            _engine(tiny, draft=DraftModel(
                short, L.init_params(short, jax.random.PRNGKey(1))))

    def test_spec_composes_with_adapters(self, tiny, draft):
        """Adapters + speculation together: the adapter-routed stream
        under spec equals the same adapter stream without spec."""
        cfg, _ = tiny
        prompts = _prompts(cfg, 2)
        ad = make_adapter(cfg, "t-a", rank=4, seed=3, scale=0.3)

        def fresh(spec):
            eng = (_spec_engine(tiny, draft, adapter_slots=2) if spec
                   else _engine(tiny, adapter_slots=2))
            eng.adapters.register(ad)
            return eng

        ref = _run(fresh(False), prompts, adapters=["t-a", None])
        eng = fresh(True)
        assert _run(eng, prompts, adapters=["t-a", None]) == ref
        assert eng.stats["spec_ticks"] > 0


# ---------------------------------------------------------------------------
# router + fleet: adapter-aware placement, failover mid-spec, digests
# ---------------------------------------------------------------------------

class TestRouterFleet:
    def test_adapter_affinity_routes_to_resident_replica(self, tiny):
        cfg, _ = tiny
        prompts = _prompts(cfg, 4)
        router = ServingRouter(lambda: _engine(tiny, adapter_slots=2),
                               num_replicas=2, probation_s=1e9)
        ad = make_adapter(cfg, "t-a", rank=4, seed=3)
        # registered + loaded ONLY on replica 1 -> placement must prefer
        # it for adapter traffic even though replica 0 is less loaded
        router.replicas[1].engine.adapters.register(ad)
        router.replicas[1].engine.adapters.ensure_loaded("t-a")
        for p in prompts:
            router.submit(p, max_new_tokens=6, adapter="t-a")
        done = router.run()
        assert len(done) == 4
        assert router.stats["adapter_routed"] == 4
        assert router.replicas[1].engine.adapters.stats["hits"] > 0
        assert router.replicas[0].engine.adapters.stats["hits"] == 0

    def test_prefetch_over_transport(self, tiny):
        """No replica knows the adapter, the transport does: placement
        prefetches it onto the chosen replica instead of failing."""
        cfg, _ = tiny
        tr = AdapterTransport()
        tr.publish(make_adapter(cfg, "t-a", rank=4, seed=3))
        router = ServingRouter(lambda: _engine(tiny, adapter_slots=2),
                               num_replicas=2, probation_s=1e9,
                               adapter_transport=tr)
        for p in _prompts(cfg, 2):
            router.submit(p, max_new_tokens=6, adapter="t-a")
        done = router.run()
        assert len(done) == 2
        assert router.stats["adapter_prefetches"] >= 1

    def test_publish_adapter_reaches_all_replicas(self, tiny):
        cfg, _ = tiny
        tr = AdapterTransport()
        router = ServingRouter(lambda: _engine(tiny, adapter_slots=2),
                               num_replicas=2, probation_s=1e9,
                               adapter_transport=tr)
        router.publish_adapter(make_adapter(cfg, "t-a", rank=4, seed=3))
        for h in router.replicas:
            assert h.engine.adapters.registered("t-a")
        assert tr.fetch("t-a") is not None

    def test_unknown_adapter_request_sheds_not_livelocks(self, tiny):
        """An adapter registered nowhere (and absent from the transport)
        can never place: the request must shed terminally, not spin in
        the pending queue forever."""
        cfg, _ = tiny
        router = ServingRouter(lambda: _engine(tiny), num_replicas=1,
                               probation_s=1e9)
        router.submit(_prompts(cfg, 1)[0], max_new_tokens=4,
                      adapter="ghost")
        done = router.run()
        assert [c.finish_reason for c in done] == ["adapter_missing"]
        assert done[0].output_tokens == []
        assert router.stats["shed"] == 1

    def test_replica_kill_mid_spec_bit_exact_failover(self, tiny, draft):
        """The ISSUE's chaos drill: kill a replica mid-speculative-
        decode — exactly one failover wave, zero replay mismatches,
        output bit-equal to a single-engine run."""
        cfg, _ = tiny
        prompts = _prompts(cfg, 4)
        base = _run(_spec_engine(tiny, draft), prompts, max_new=12)
        # spec ticks emit up to k+1 tokens, so streams finish in few
        # guarded steps — the kill must land early to hit them mid-decode
        chaos.reconfigure("replica:kill@victim=0;call=2")
        try:
            router = ServingRouter(lambda: _spec_engine(tiny, draft),
                                   num_replicas=2, probation_s=1e9,
                                   tenant_weights={"default": 4})
            rids = [router.submit(p, max_new_tokens=12) for p in prompts]
            done = {c.rid: c.output_tokens for c in router.run()}
        finally:
            chaos.reconfigure("")
        assert [done.get(r) for r in rids] == base
        # both streams the dead replica held fail over, each counted
        assert router.stats["failovers"] == 2
        assert router.stats["mismatches"] == 0
        assert router.stats["shed"] == 0

    def test_summary_sections(self, tiny, draft):
        cfg, _ = tiny
        eng = _spec_engine(tiny, draft, adapter_slots=2)
        eng.adapters.register(make_adapter(cfg, "t-a", rank=4, seed=3))
        _run(eng, _prompts(cfg, 2), adapters=["t-a", None])
        s = obs.summary()
        ad = s["adapters"]
        for k in ("registered", "loads", "swaps", "evictions", "hits",
                  "resident", "bytes_in_use", "bytes_total"):
            assert k in ad
        assert ad["loads"] >= 1
        sp = s["spec"]
        for k in ("ticks", "proposed", "accepted", "bonus",
                  "draft_steps", "acceptance_rate"):
            assert k in sp
        assert sp["ticks"] >= 1

    def test_fleet_summary_per_adapter_digest(self, tiny):
        from paddle_tpu.observability.fleet import fleet_summary

        cfg, _ = tiny
        eng = _engine(tiny, adapter_slots=2)
        eng.adapters.register(make_adapter(cfg, "digest-t", rank=4,
                                           seed=3))
        _run(eng, _prompts(cfg, 2), adapters=["digest-t", "digest-t"])
        fs = fleet_summary()
        d = fs["adapters"]["digest-t"]
        assert d["loads"] >= 1 and d["hits"] >= 1
        assert d["resident_ranks"] >= 1
        assert "spec_acceptance_rate" in fs

    def test_replica_snapshot_has_adapter_fields(self, tiny, draft):
        cfg, _ = tiny
        router = ServingRouter(lambda: _spec_engine(tiny, draft,
                                                    adapter_slots=2),
                               num_replicas=1, probation_s=1e9)
        router.replicas[0].engine.adapters.register(
            make_adapter(cfg, "t-a", rank=4, seed=3))
        for p in _prompts(cfg, 2):
            router.submit(p, max_new_tokens=6, adapter="t-a")
        router.run()
        snap = router.replicas[0].snapshot()
        assert snap["adapters_resident"] == ["t-a"]
        assert snap["adapter_bytes_in_use"] > 0
        assert snap["adapter_hits"] >= 1
        assert "spec_acceptance_rate" in snap

"""Comm watchdog: hang detection + diagnostics (VERDICT r2 task 5).

Reference analog: CommTaskManager / NCCLCommTask timeout detection
(`paddle/phi/core/distributed/comm_task_manager.h:37`,
`nccl_comm_task.h:53`).
"""
import os
import subprocess
import sys
import tempfile
import time

import pytest

import paddle_tpu as paddle
from tests.test_multiproc_collective import _free_port
from paddle_tpu.distributed import comm_watchdog as W

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multiproc", "watchdog_worker.py")


def test_task_lifecycle_no_timeout():
    mgr = W.CommTaskManager()
    tid = mgr.start_task("all_reduce", 0, 0, (4,), "float32", timeout=30.0)
    assert tid is not None
    assert len(mgr.in_flight()) == 1
    assert "op=all_reduce" in mgr.in_flight()[0].describe()
    mgr.end_task(tid)
    assert not mgr.in_flight()


def test_disabled_by_default():
    mgr = W.CommTaskManager()
    assert mgr.start_task("all_reduce", 0, 0, (4,), "float32") is None


def test_timeout_fires_diagnostics(capsys):
    paddle.set_flags({"FLAGS_comm_watchdog_abort": False})
    try:
        mgr = W.CommTaskManager()
        tid = mgr.start_task("broadcast", 3, 1, (2, 2), "float32",
                             timeout=0.3, extra="src=0")
        deadline = time.time() + 10
        while mgr.in_flight() and time.time() < deadline:
            time.sleep(0.1)
        assert not mgr.in_flight(), "task never expired"
        time.sleep(0.3)  # let the watchdog thread finish printing
        err = capsys.readouterr().err
        assert "COLLECTIVE TIMEOUT" in err
        assert "op=broadcast" in err and "rank=1" in err
        assert "shape=(2, 2)" in err and "src=0" in err
        mgr.end_task(tid)
    finally:
        paddle.set_flags({"FLAGS_comm_watchdog_abort": True})


def test_comm_task_context_manager():
    paddle.set_flags({"FLAGS_comm_timeout": 60.0})
    try:
        with W.comm_task("all_gather", 0, 0, (8,), "float32"):
            assert len(W.comm_task_manager().in_flight()) >= 1
        assert all(t.op != "all_gather"
                   for t in W.comm_task_manager().in_flight())
    finally:
        paddle.set_flags({"FLAGS_comm_timeout": 0.0})


def test_stalled_rank_aborted_with_named_diagnostics():
    """End-to-end: 2 real processes; rank 1 never joins the allreduce; rank
    0's watchdog dumps op/rank/shape diagnostics and SIGABRTs, failing the
    pod (non-zero launcher exit)."""
    with tempfile.TemporaryDirectory() as d:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["PADDLE_MASTER_PORT"] = str(_free_port())
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        log_dir = os.path.join(d, "log")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "1", "--nproc_per_node", "2", "--max_restart", "0",
             "--log_dir", log_dir, WORKER],
            env=env, cwd=REPO, timeout=240, capture_output=True, text=True)
        assert proc.returncode != 0, (
            f"launcher should fail when a rank hangs; stdout={proc.stdout}")
        with open(os.path.join(log_dir, "workerlog.0")) as f:
            log0 = f.read()
        assert "COLLECTIVE TIMEOUT" in log0, log0[-2000:]
        assert "op=all_reduce" in log0
        assert "rank=0" in log0
        assert "shape=(4,)" in log0
        assert "UNREACHABLE" not in log0

"""Vision model zoo forward-shape + trainability tests.

Covers the round-5 zoo additions (alexnet, squeezenet, mobilenet v1/v3,
shufflenetv2, densenet, googlenet, inceptionv3) the same way the reference's
test/legacy_test/test_vision_models.py exercises its zoo: build, forward,
check the logits shape; one backward pass on a small model proves the graph
is differentiable end to end. Reference: python/paddle/vision/models/*.
"""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models as M


def _img(size=64, batch=1):
    rs = np.random.RandomState(0)
    return paddle.to_tensor(rs.randn(batch, 3, size, size).astype(np.float32))


@pytest.mark.parametrize("factory,kwargs,size", [
    (M.alexnet, {}, 224),
    (M.squeezenet1_0, {}, 96),
    (M.squeezenet1_1, {}, 96),
    (M.mobilenet_v1, {"scale": 0.25}, 64),
    (M.mobilenet_v3_small, {"scale": 0.5}, 64),
    (M.mobilenet_v3_large, {"scale": 0.35}, 64),
    (M.shufflenet_v2_x0_25, {}, 64),
    (M.shufflenet_v2_x1_0, {}, 64),
    (M.densenet121, {}, 64),
], ids=["alexnet", "squeezenet1_0", "squeezenet1_1", "mobilenet_v1",
        "mobilenet_v3_small", "mobilenet_v3_large", "shufflenet_v2_x0_25",
        "shufflenet_v2_x1_0", "densenet121"])
def test_zoo_forward_shape(factory, kwargs, size):
    model = factory(num_classes=10, **kwargs)
    model.eval()
    out = model(_img(size))
    assert tuple(out.shape) == (1, 10)
    assert np.isfinite(out.numpy()).all()


def test_googlenet_aux_heads():
    model = M.googlenet(num_classes=10)
    model.eval()
    out, aux1, aux2 = model(_img(224))
    assert tuple(out.shape) == (1, 10)
    assert tuple(aux1.shape) == (1, 10)
    assert tuple(aux2.shape) == (1, 10)


def test_inception_v3_forward():
    model = M.inception_v3(num_classes=10)
    model.eval()
    out = model(_img(299))
    assert tuple(out.shape) == (1, 10)


def test_zoo_with_pool_false_and_headless():
    model = M.squeezenet1_1(num_classes=0, with_pool=False)
    model.eval()
    out = model(_img(96))
    assert len(out.shape) == 4 and out.shape[1] == 512


def test_zoo_backward_trains():
    model = M.mobilenet_v1(scale=0.25, num_classes=10)
    model.train()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x = _img(64, batch=2)
    y = paddle.to_tensor(np.array([1, 3]))
    first = None
    for _ in range(3):
        loss = paddle.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = float(loss.numpy())
    assert float(loss.numpy()) < first


def test_shufflenet_swish_uses_swish_activation():
    model = M.shufflenet_v2_swish(num_classes=4)
    kinds = [type(layer).__name__ for layer in model.sublayers()]
    assert "Swish" in kinds and "ReLU" not in kinds
    model.eval()
    out = model(_img(64))
    assert tuple(out.shape) == (1, 4)


def test_zoo_state_dict_roundtrip():
    model = M.mobilenet_v3_small(scale=0.5, num_classes=4)
    clone = M.mobilenet_v3_small(scale=0.5, num_classes=4)
    clone.set_state_dict(model.state_dict())
    model.eval()
    clone.eval()
    x = _img(64)
    np.testing.assert_allclose(model(x).numpy(), clone(x).numpy(), rtol=1e-6)

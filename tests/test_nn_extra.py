"""Round-5 nn tail tests: 1D/3D pools, unpools, transposed convs, dropout
variants, loss modules — semantics pinned against torch where torch has the
same operator, shape/finiteness otherwise.
Reference: python/paddle/nn/layer/* and nn/functional/*.
"""
from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")

RS = np.random.RandomState


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestPools:
    def test_adaptive_avg_pool1d_vs_torch(self):
        x = RS(0).randn(2, 3, 11).astype(np.float32)
        got = F.adaptive_avg_pool1d(_t(x), 4).numpy()
        ref = torch.nn.functional.adaptive_avg_pool1d(torch.tensor(x),
                                                      4).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_adaptive_max_pool1d_vs_torch(self):
        x = RS(1).randn(2, 3, 11).astype(np.float32)
        got = F.adaptive_max_pool1d(_t(x), 4).numpy()
        ref = torch.nn.functional.adaptive_max_pool1d(torch.tensor(x),
                                                      4).numpy()
        np.testing.assert_allclose(got, ref)

    def test_adaptive_avg_pool3d_vs_torch(self):
        x = RS(2).randn(1, 2, 5, 7, 6).astype(np.float32)
        got = F.adaptive_avg_pool3d(_t(x), (2, 3, 4)).numpy()
        ref = torch.nn.functional.adaptive_avg_pool3d(
            torch.tensor(x), (2, 3, 4)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_adaptive_max_pool3d_vs_torch(self):
        x = RS(3).randn(1, 2, 5, 7, 6).astype(np.float32)
        got = F.adaptive_max_pool3d(_t(x), (2, 3, 4)).numpy()
        ref = torch.nn.functional.adaptive_max_pool3d(
            torch.tensor(x), (2, 3, 4)).numpy()
        np.testing.assert_allclose(got, ref)

    def test_max_avg_pool3d_vs_torch(self):
        x = RS(4).randn(1, 2, 6, 6, 6).astype(np.float32)
        np.testing.assert_allclose(
            F.max_pool3d(_t(x), 2).numpy(),
            torch.nn.functional.max_pool3d(torch.tensor(x), 2).numpy())
        np.testing.assert_allclose(
            F.avg_pool3d(_t(x), 2).numpy(),
            torch.nn.functional.avg_pool3d(torch.tensor(x), 2).numpy(),
            rtol=1e-6)

    def test_max_unpool2d_roundtrip(self):
        x = RS(5).randn(1, 2, 6, 6).astype(np.float32)
        pooled, idx = F.max_pool2d_with_index(_t(x), 2)
        up = F.max_unpool2d(pooled, idx, 2, output_size=[6, 6]).numpy()
        tp, ti = torch.nn.functional.max_pool2d(torch.tensor(x), 2,
                                                return_indices=True)
        ref = torch.nn.functional.max_unpool2d(tp, ti, 2,
                                               output_size=[6, 6]).numpy()
        np.testing.assert_allclose(up, ref)

    def test_lp_pool1d_vs_torch(self):
        x = np.abs(RS(6).randn(2, 3, 8)).astype(np.float32)
        got = F.lp_pool1d(_t(x), 2.0, 2).numpy()
        ref = torch.nn.functional.lp_pool1d(torch.tensor(x), 2.0, 2).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_pool_layers_forward(self):
        x = _t(RS(7).randn(1, 2, 6, 6, 6).astype(np.float32))
        assert list(nn.MaxPool3D(2)(x).shape) == [1, 2, 3, 3, 3]
        assert list(nn.AvgPool3D(2)(x).shape) == [1, 2, 3, 3, 3]
        assert list(nn.AdaptiveAvgPool3D(1)(x).shape) == [1, 2, 1, 1, 1]
        x1 = _t(RS(8).randn(1, 2, 9).astype(np.float32))
        assert list(nn.AdaptiveAvgPool1D(4)(x1).shape) == [1, 2, 4]
        assert list(nn.LPPool1D(2.0, 3)(x1).shape) == [1, 2, 3]


class TestConvTranspose:
    def test_conv1d_transpose_vs_torch(self):
        x = RS(9).randn(2, 3, 8).astype(np.float32)
        w = RS(10).randn(3, 4, 3).astype(np.float32)
        got = F.conv1d_transpose(_t(x), _t(w), stride=2, padding=1).numpy()
        ref = torch.nn.functional.conv_transpose1d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_conv_transpose_layers(self):
        m1 = nn.Conv1DTranspose(3, 6, 3, stride=2)
        y = m1(_t(RS(11).randn(1, 3, 5).astype(np.float32)))
        assert y.shape[1] == 6
        m3 = nn.Conv3DTranspose(2, 4, 3)
        y3 = m3(_t(RS(12).randn(1, 2, 4, 4, 4).astype(np.float32)))
        assert y3.shape[1] == 4 and y3.shape[2] == 6


class TestDropoutVariants:
    def test_alpha_dropout_stats(self):
        x = _t(RS(13).randn(20000).astype(np.float32))
        y = F.alpha_dropout(x, p=0.3, training=True).numpy()
        # self-normalizing: mean/var approximately preserved
        assert abs(y.mean()) < 0.1 and abs(y.std() - 1.0) < 0.15
        y_eval = F.alpha_dropout(x, p=0.3, training=False)
        np.testing.assert_allclose(y_eval.numpy(), x.numpy())

    def test_dropout3d_drops_whole_channels(self):
        x = _t(np.ones((2, 8, 3, 3, 3), np.float32))
        y = nn.Dropout3D(0.5)(x).numpy()
        per_channel = y.reshape(2, 8, -1)
        for b in range(2):
            for c in range(8):
                vals = np.unique(per_channel[b, c])
                assert len(vals) == 1  # all-kept (scaled) or all-dropped


class TestLosses:
    def test_cosine_embedding_loss_vs_torch(self):
        a = RS(14).randn(4, 6).astype(np.float32)
        b = RS(15).randn(4, 6).astype(np.float32)
        lab = np.array([1, -1, 1, -1], np.int64)
        got = float(F.cosine_embedding_loss(_t(a), _t(b), _t(lab),
                                            margin=0.2).numpy())
        ref = float(torch.nn.functional.cosine_embedding_loss(
            torch.tensor(a), torch.tensor(b), torch.tensor(lab),
            margin=0.2))
        assert abs(got - ref) < 1e-5

    def test_hinge_embedding_loss_vs_torch(self):
        x = RS(16).randn(4, 5).astype(np.float32)
        lab = np.where(RS(17).rand(4, 5) < 0.5, 1.0, -1.0).astype(np.float32)
        got = float(F.hinge_embedding_loss(_t(x), _t(lab)).numpy())
        ref = float(torch.nn.functional.hinge_embedding_loss(
            torch.tensor(x), torch.tensor(lab)))
        assert abs(got - ref) < 1e-5

    def test_soft_margin_loss_vs_torch(self):
        x = RS(18).randn(6).astype(np.float32)
        lab = np.where(RS(19).rand(6) < 0.5, 1.0, -1.0).astype(np.float32)
        got = float(F.soft_margin_loss(_t(x), _t(lab)).numpy())
        ref = float(torch.nn.functional.soft_margin_loss(
            torch.tensor(x), torch.tensor(lab)))
        assert abs(got - ref) < 1e-5

    def test_multi_margin_loss_vs_torch(self):
        x = RS(20).randn(5, 7).astype(np.float32)
        lab = RS(21).randint(0, 7, (5,))
        got = float(F.multi_margin_loss(_t(x), _t(lab)).numpy())
        ref = float(torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(lab)))
        assert abs(got - ref) < 1e-5

    def test_multi_label_soft_margin_vs_torch(self):
        x = RS(22).randn(4, 6).astype(np.float32)
        lab = (RS(23).rand(4, 6) < 0.5).astype(np.float32)
        got = float(F.multi_label_soft_margin_loss(_t(x), _t(lab)).numpy())
        ref = float(torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(x), torch.tensor(lab)))
        assert abs(got - ref) < 1e-5

    def test_poisson_nll_vs_torch(self):
        x = RS(24).randn(8).astype(np.float32)
        lab = np.abs(RS(25).randn(8)).astype(np.float32)
        got = float(F.poisson_nll_loss(_t(x), _t(lab)).numpy())
        ref = float(torch.nn.functional.poisson_nll_loss(
            torch.tensor(x), torch.tensor(lab)))
        assert abs(got - ref) < 1e-5

    def test_gaussian_nll_vs_torch(self):
        x = RS(26).randn(8).astype(np.float32)
        lab = RS(27).randn(8).astype(np.float32)
        var = np.abs(RS(28).randn(8)).astype(np.float32) + 0.1
        got = float(F.gaussian_nll_loss(_t(x), _t(lab), _t(var)).numpy())
        ref = float(torch.nn.functional.gaussian_nll_loss(
            torch.tensor(x), torch.tensor(lab), torch.tensor(var)))
        assert abs(got - ref) < 1e-5

    def test_triplet_margin_vs_torch(self):
        a = RS(29).randn(4, 6).astype(np.float32)
        p = RS(30).randn(4, 6).astype(np.float32)
        n = RS(31).randn(4, 6).astype(np.float32)
        got = float(F.triplet_margin_loss(_t(a), _t(p), _t(n)).numpy())
        ref = float(torch.nn.functional.triplet_margin_loss(
            torch.tensor(a), torch.tensor(p), torch.tensor(n)))
        assert abs(got - ref) < 1e-4
        got_l = float(nn.TripletMarginLoss(swap=True)(_t(a), _t(p),
                                                      _t(n)).numpy())
        ref_l = float(torch.nn.TripletMarginLoss(swap=True)(
            torch.tensor(a), torch.tensor(p), torch.tensor(n)))
        assert abs(got_l - ref_l) < 1e-4

    def test_pairwise_distance_vs_torch(self):
        a = RS(32).randn(4, 6).astype(np.float32)
        b = RS(33).randn(4, 6).astype(np.float32)
        got = F.pairwise_distance(_t(a), _t(b)).numpy()
        ref = torch.nn.functional.pairwise_distance(
            torch.tensor(a), torch.tensor(b)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_sigmoid_focal_loss_shape_and_value(self):
        logit = RS(34).randn(4, 3).astype(np.float32)
        lab = (RS(35).rand(4, 3) < 0.3).astype(np.float32)
        loss = float(F.sigmoid_focal_loss(_t(logit), _t(lab)).numpy())
        # closed-form recompute in numpy
        p = 1 / (1 + np.exp(-logit))
        ce = -(lab * np.log(p) + (1 - lab) * np.log(1 - p))
        p_t = p * lab + (1 - p) * (1 - lab)
        a_t = 0.25 * lab + 0.75 * (1 - lab)
        want = float((a_t * (1 - p_t) ** 2.0 * ce).sum())
        assert abs(loss - want) < 1e-3

    def test_dice_loss_range(self):
        probs = paddle.nn.functional.softmax(
            _t(RS(36).randn(3, 5).astype(np.float32)), axis=-1)
        lab = _t(RS(37).randint(0, 5, (3, 1)))
        loss = float(F.dice_loss(probs, lab).numpy())
        assert 0.0 <= loss <= 1.0

    def test_adaptive_log_softmax_vs_torch(self):
        in_f, n_cls = 8, 12
        tm = torch.nn.AdaptiveLogSoftmaxWithLoss(in_f, n_cls, cutoffs=[4, 8],
                                                 div_value=2.0)
        m = nn.AdaptiveLogSoftmaxWithLoss(in_f, n_cls, cutoffs=[4, 8],
                                          div_value=2.0)
        # copy torch's weights in (torch head.weight is [out, in])
        m.head_weight.set_value(
            tm.head.weight.detach().numpy().T.astype(np.float32))
        for ci in range(2):
            w1 = tm.tail[ci][0].weight.detach().numpy().T.astype(np.float32)
            w2 = tm.tail[ci][1].weight.detach().numpy().T.astype(np.float32)
            m.tail_weights[ci][0].set_value(w1)
            m.tail_weights[ci][1].set_value(w2)
        x = RS(38).randn(6, in_f).astype(np.float32)
        y = RS(39).randint(0, n_cls, (6,))
        out, loss = m(_t(x), _t(y))
        tout = tm(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(out.numpy(),
                                   tout.output.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        assert abs(float(loss.numpy()) - float(tout.loss)) < 1e-4


class TestMisc:
    def test_zeropad2d_and_pad_layers(self):
        x = _t(RS(40).randn(1, 2, 3, 3).astype(np.float32))
        y = F.zeropad2d(x, [1, 2, 3, 4])
        assert list(y.shape) == [1, 2, 10, 6]
        x1 = _t(RS(41).randn(1, 2, 5).astype(np.float32))
        assert list(nn.ZeroPad1D(2)(x1).shape) == [1, 2, 9]
        x3 = _t(RS(42).randn(1, 2, 3, 3, 3).astype(np.float32))
        assert list(nn.ZeroPad3D(1)(x3).shape) == [1, 2, 5, 5, 5]

    def test_upsampling_layers(self):
        x = _t(RS(43).randn(1, 2, 4, 4).astype(np.float32))
        assert list(nn.UpsamplingNearest2D(scale_factor=2)(x).shape) == \
            [1, 2, 8, 8]
        assert list(nn.UpsamplingBilinear2D(size=[6, 6])(x).shape) == \
            [1, 2, 6, 6]

    def test_bilinear_layer_vs_torch(self):
        m = nn.Bilinear(3, 4, 5, bias_attr=False)
        tw = RS(44).randn(5, 3, 4).astype(np.float32)
        m.weight.set_value(tw)
        x1 = RS(45).randn(2, 3).astype(np.float32)
        x2 = RS(46).randn(2, 4).astype(np.float32)
        got = m(_t(x1), _t(x2)).numpy()
        ref = torch.nn.functional.bilinear(
            torch.tensor(x1), torch.tensor(x2), torch.tensor(tw)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_parameter_dict(self):
        pd = nn.ParameterDict({"a": paddle.create_parameter([2, 2],
                                                            "float32")})
        pd["b"] = paddle.create_parameter([3], "float32")
        assert set(pd.keys()) == {"a", "b"}
        assert len(list(pd.values())) == 2
        assert len(pd) == 2

    def test_unflatten_softmax2d_channelshuffle(self):
        x = _t(RS(47).randn(2, 6, 4).astype(np.float32))
        assert list(nn.Unflatten(1, [2, 3])(x).shape) == [2, 2, 3, 4]
        img = _t(RS(48).randn(1, 4, 3, 3).astype(np.float32))
        s = nn.Softmax2D()(img).numpy()
        np.testing.assert_allclose(s.sum(axis=1), np.ones((1, 3, 3)),
                                   rtol=1e-5)
        assert list(nn.ChannelShuffle(2)(img).shape) == [1, 4, 3, 3]

    def test_rrelu_modes(self):
        x = _t(RS(49).randn(100).astype(np.float32))
        m = nn.RReLU()
        m.eval()
        y = m(x).numpy()
        neg = x.numpy() < 0
        slope = np.mean((1 / 8 + 1 / 3) / 2)
        np.testing.assert_allclose(y[neg], x.numpy()[neg] * slope, rtol=1e-5)

    def test_inplace_activations(self):
        z = _t(np.array([-1.0, 2.0], np.float32))
        F.relu_(z)
        np.testing.assert_allclose(z.numpy(), [0.0, 2.0])
        w = _t(np.array([-5.0, 5.0], np.float32))
        F.hardtanh_(w)
        np.testing.assert_allclose(w.numpy(), [-1.0, 1.0])

    def test_rnnt_loss_runs(self):
        B, T, U, V = 2, 4, 3, 5
        acts = _t(RS(50).randn(B, T, U, V).astype(np.float32))
        labels = _t(RS(51).randint(1, V, (B, U - 1)).astype(np.int32))
        in_len = _t(np.full((B,), T, np.int32))
        lab_len = _t(np.full((B,), U - 1, np.int32))
        loss = F.rnnt_loss(acts, labels, in_len, lab_len)
        assert np.isfinite(float(loss.numpy()))

    def test_dynamic_decode_beam_search(self):
        V, H = 7, 5

        class Cell(nn.Layer):
            def __init__(self):
                super().__init__()
                self.proj = nn.Linear(H, H)
                self.out = nn.Linear(H, V)

            def forward(self, tok, state):
                h = paddle.nn.functional.relu(self.proj(state))
                return self.out(h), h

        cell = Cell()
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                   beam_size=2)
        init = paddle.to_tensor(RS(52).randn(3, H).astype(np.float32))
        ids, state = nn.dynamic_decode(dec, init, max_step_num=5)
        assert ids.shape[0] == 3 and ids.shape[2] == 2

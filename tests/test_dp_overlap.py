"""Overlapped DP gradient sync + ZeRO-1 sharded update (parallel.py reducer).

Covers the three pillars of the rebuilt data-parallel hot path on the
8-virtual-device CPU mesh (conftest.py):

- overlap: grad-final hooks issue each bucket's collective during backward;
  step() drains Task handles instead of running a post-backward barrier
- sharded update (FLAGS_dp_shard_update, ZeRO-1): reduce-scattered flat grad
  shards + fused optimizer step on the owned shard + all-gather back, bit
  exact vs the replicated path for every optimizer
- caching: persistent bucket plan + jitted flat pack/unpack executables,
  zero rebuilds in steady state
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import observability as obs
from paddle_tpu.core import flags


@pytest.fixture(scope="module", autouse=True)
def _env():
    os.environ["PADDLE_TRAINERS_NUM"] = "8"
    dist.collective.destroy_process_group()
    dist.init_parallel_env()
    yield
    os.environ.pop("PADDLE_TRAINERS_NUM", None)
    dist.collective.destroy_process_group()


@pytest.fixture(autouse=True)
def _reset_flags():
    yield
    flags.set_flags({"dp_overlap": True, "dp_shard_update": False,
                     "dp_grad_comm_dtype": "", "chaos_spec": "",
                     "comm_timeout": 0.0, "watchdog_policy": "",
                     "comm_watchdog_abort": False})


def _metric(name, labels=None):
    return obs.registry().value(name, labels or {})


class _MLP(nn.Layer):
    def __init__(self, din=8, dhid=16, dout=4):
        super().__init__()
        self.l1 = nn.Linear(din, dhid)
        self.l2 = nn.Linear(dhid, dout)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return self.l2(F.relu(self.l1(x)))


def _train(opt_cls, shard, steps=3, group=None, seed=7, accumulate=0,
           okw=None, comm_mb=25, last_mb=1):
    """One training run; returns (final param arrays, wrapper, dp)."""
    flags.set_flags({"dp_shard_update": shard})
    paddle.seed(seed)
    m = _MLP()
    d = dist.DataParallel(m, group=group or dist.get_group(0),
                          comm_buffer_size_MB=comm_mb,
                          last_comm_buffer_size_MB=last_mb)
    o = opt_cls(learning_rate=0.05, parameters=m.parameters(), **(okw or {}))
    so = dist.sharded_update(o, d) if shard else o
    for i in range(steps):
        x = paddle.to_tensor(
            np.random.RandomState(i).randn(8, 8).astype(np.float32))
        if accumulate:
            with d.no_sync():
                for j in range(accumulate):
                    xa = paddle.to_tensor(np.random.RandomState(100 + i * 10 + j)
                                          .randn(8, 8).astype(np.float32))
                    d(xa).mean().backward()
        d(x).mean().backward()
        so.step()
        so.clear_grad()
    flags.set_flags({"dp_shard_update": False})
    return [np.asarray(p._data) for p in m.parameters()], so, d


# the 13 optimizers whose sharded update must match the replicated path
# bit for bit (Lamb goes through the documented replicated fallback)
PARITY_OPTIMIZERS = [opt.SGD, opt.Momentum, opt.Adam, opt.AdamW, opt.Adagrad,
                     opt.RMSProp, opt.Adadelta, opt.Adamax, opt.Lamb,
                     opt.ASGD, opt.NAdam, opt.RAdam, opt.Rprop]


class TestShardedUpdateParity:
    @pytest.mark.parametrize(
        "opt_cls", PARITY_OPTIMIZERS, ids=lambda c: c.__name__)
    def test_bit_exact_vs_replicated(self, opt_cls, recwarn):
        w_ref, _, _ = _train(opt_cls, shard=False)
        w_sh, _, _ = _train(opt_cls, shard=True)
        for i, (a, b) in enumerate(zip(w_ref, w_sh)):
            assert np.array_equal(a, b), (
                f"{opt_cls.__name__} param {i}: "
                f"maxdiff {np.max(np.abs(a - b))}")

    @pytest.mark.parametrize("nranks", [2, 4])
    def test_parity_on_subgroup(self, nranks):
        g = dist.new_group(list(range(nranks)))
        assert g.nranks == nranks
        w_ref, _, _ = _train(opt.Adam, shard=False, group=g)
        w_sh, _, _ = _train(opt.Adam, shard=True, group=g)
        for a, b in zip(w_ref, w_sh):
            assert np.array_equal(a, b)

    def test_lamb_falls_back_with_one_warning(self):
        with pytest.warns(UserWarning, match="flat-shard"):
            _, so, _ = _train(opt.Lamb, shard=True)
        assert so._flat_ok is False

    def test_optimizer_state_is_sharded(self):
        _, so_ref, _ = _train(opt.Adam, shard=False)
        _, so, _ = _train(opt.Adam, shard=True)
        sharded_bytes = so.optimizer_state_bytes_per_device()
        # replicated: every device holds the full moment1+moment2
        full_bytes = sum(
            int(getattr(a, "nbytes", 0))
            for store in so_ref._accumulators.values()
            for a in store.values())
        assert 0 < sharded_bytes < full_bytes
        # flat pseudo-param accumulators, one pair per bucket
        keys = sorted(so.state_dict().keys())
        assert any(k.startswith("_dp_flat_b") for k in keys)

    def test_state_dict_roundtrip_under_sharding(self):
        flags.set_flags({"dp_shard_update": True})
        g = dist.get_group(0)

        def run(steps, state=None):
            paddle.seed(11)
            m = _MLP()
            d = dist.DataParallel(m, group=g)
            o = opt.Adam(learning_rate=0.05, parameters=m.parameters())
            so = dist.sharded_update(o, d)
            for i in range(steps):
                x = paddle.to_tensor(
                    np.random.RandomState(i).randn(8, 8).astype(np.float32))
                d(x).mean().backward()
                if state is not None and i == 2:
                    so.set_state_dict(state)
                so.step()
                so.clear_grad()
            return [np.asarray(p._data) for p in m.parameters()], so

        w_full, so = run(4)
        sd = so.state_dict()
        # round-trip: loading the snapshot reproduces the same trajectory
        np_sd = {k: np.asarray(v) for k, v in sd.items()
                 if not np.isscalar(v) and hasattr(v, "shape")}
        w_again, so2 = run(4)
        sd2 = so2.state_dict()
        assert sorted(sd.keys()) == sorted(sd2.keys())
        for k, v in np_sd.items():
            assert np.array_equal(v, np.asarray(sd2[k])), k
        for a, b in zip(w_full, w_again):
            assert np.array_equal(a, b)


class TestOverlap:
    def test_hooks_issue_during_backward(self):
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m)
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        d(x).mean().backward()
        # collectives were issued from grad-final hooks, before any explicit
        # sync: the Task handles are outstanding right after backward
        assert d._reducer._outstanding
        d.sync_gradients()
        assert not d._reducer._outstanding

    def test_barrier_mode_issues_at_sync(self):
        flags.set_flags({"dp_overlap": False})
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m)
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        d(x).mean().backward()
        assert not d._reducer._outstanding
        d.sync_gradients()
        assert m.l1.weight._grad is not None
        flags.set_flags({"dp_overlap": True})

    def test_overlap_matches_barrier(self):
        w_overlap, _, _ = _train(opt.Adam, shard=False)
        flags.set_flags({"dp_overlap": False})
        try:
            w_barrier, _, _ = _train(opt.Adam, shard=False)
        finally:
            flags.set_flags({"dp_overlap": True})
        for a, b in zip(w_overlap, w_barrier):
            assert np.array_equal(a, b)

    def test_step_drains_without_explicit_sync(self):
        """Optimizer.step's pre-step hook is the drain; no sync_gradients."""
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m)
        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        before = [np.asarray(p._data).copy() for p in m.parameters()]
        d(paddle.to_tensor(np.ones((4, 8), np.float32))).mean().backward()
        o.step()
        assert not d._reducer._outstanding
        after = [np.asarray(p._data) for p in m.parameters()]
        assert any(not np.array_equal(a, b) for a, b in zip(before, after))

    def test_overlap_efficiency_gauge_published(self):
        _train(opt.SGD, shard=False, steps=2)
        s = obs.summary()
        assert 0.0 <= s["dp_overlap_efficiency"] <= 1.0
        assert s["dp_bytes_reduced"] > 0


class TestNoSync:
    def test_no_sync_suppresses_hook_collectives(self):
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m)
        before = _metric("paddle_dp_bucket_comms_total",
                         {"op": "all_reduce"})
        with d.no_sync():
            d(paddle.to_tensor(np.ones((4, 8), np.float32))).mean().backward()
            assert not d._reducer._outstanding
            d.sync_gradients()  # also suppressed inside the context
        assert _metric("paddle_dp_bucket_comms_total",
                       {"op": "all_reduce"}) == before

    def test_accumulation_parity(self):
        # k accumulated backwards under no_sync + one synced backward must
        # match the same schedule on the sharded path bit for bit (AVG is
        # linear, so reducing the k-step total is exact)
        w_ref, _, _ = _train(opt.Momentum, shard=False, accumulate=2)
        w_sh, _, _ = _train(opt.Momentum, shard=True, accumulate=2)
        for a, b in zip(w_ref, w_sh):
            assert np.array_equal(a, b)


class TestStepDrain:
    def test_barrier_mode_step_issues_reduction(self):
        """Vanilla backward(); step() with FLAGS_dp_overlap=0 must reduce:
        the pre-step hook issues the unissued buckets, not just wait."""
        flags.set_flags({"dp_overlap": False})
        try:
            paddle.seed(3)
            m = _MLP()
            d = dist.DataParallel(m)
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
            before = _metric("paddle_dp_bucket_comms_total",
                             {"op": "all_reduce"})
            d(paddle.to_tensor(np.ones((4, 8), np.float32))).mean().backward()
            assert not d._reducer._outstanding  # nothing issued in backward
            o.step()
            assert _metric("paddle_dp_bucket_comms_total",
                           {"op": "all_reduce"}) > before
        finally:
            flags.set_flags({"dp_overlap": True})

    def test_explicit_sync_then_step_reduces_once(self):
        """sync_gradients() followed by step() must not re-issue the
        bucket collectives from the pre-step drain."""
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m)
        o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
        d(paddle.to_tensor(np.ones((4, 8), np.float32))).mean().backward()
        d.sync_gradients()
        after_sync = _metric("paddle_dp_bucket_comms_total",
                             {"op": "all_reduce"})
        o.step()
        assert _metric("paddle_dp_bucket_comms_total",
                       {"op": "all_reduce"}) == after_sync


class TestPartialBuckets:
    """Partially-used buckets (find_unused_parameters-style steps where
    only a sub-path of the model ran backward)."""

    def _partial_backward(self, m):
        # only l1 participates: l2's params never get grads this step
        import paddle_tpu.nn.functional as F

        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        F.relu(m.l1(x)).mean().backward()

    def test_fallback_clears_ready_state(self):
        paddle.seed(3)
        m = _MLP()
        d = dist.DataParallel(m, find_unused_parameters=True)
        self._partial_backward(m)
        d.sync_gradients()
        plan = d._reducer._ensure_plan()
        # no stale per-step state may survive the fallback reduction
        for b in plan.buckets:
            assert not b.ready and not b.issued
        # a following full step is unaffected by the partial one
        d(paddle.to_tensor(np.ones((4, 8), np.float32))).mean().backward()
        d.sync_gradients()
        for p in m.parameters():
            assert p._grad is not None
        for b in plan.buckets:
            assert not b.ready and not b.issued

    def test_sharded_partial_bucket_params_still_step(self):
        """Under FLAGS_dp_shard_update, params WITH grads in a
        partially-used bucket get their optimizer update (replicated),
        matching what the legacy replicated path does."""
        flags.set_flags({"dp_shard_update": True})
        try:
            paddle.seed(3)
            m = _MLP()
            d = dist.DataParallel(m, find_unused_parameters=True)
            o = opt.SGD(learning_rate=0.1, parameters=m.parameters())
            so = dist.sharded_update(o, d)
            l1_before = [np.asarray(p._data).copy()
                         for p in (m.l1.weight, m.l1.bias)]
            l2_before = [np.asarray(p._data).copy()
                         for p in (m.l2.weight, m.l2.bias)]
            self._partial_backward(m)
            so.step()
            l1_after = [np.asarray(p._data)
                        for p in (m.l1.weight, m.l1.bias)]
            l2_after = [np.asarray(p._data)
                       for p in (m.l2.weight, m.l2.bias)]
            assert any(not np.array_equal(a, b)
                       for a, b in zip(l1_before, l1_after))
            for a, b in zip(l2_before, l2_after):
                assert np.array_equal(a, b)
        finally:
            flags.set_flags({"dp_shard_update": False})


class TestBucketLayout:
    def test_comm_buffer_size_honored(self):
        paddle.seed(5)
        m = nn.Sequential(*[nn.Linear(64, 64) for _ in range(4)])
        # 64*64 fp32 weights = 16 KiB each; a 0.02 MB cap forces multiple
        # buckets, each within the cap (down to single-param granularity)
        d = dist.DataParallel(m, comm_buffer_size_MB=0.02,
                              last_comm_buffer_size_MB=0.001)
        plan = d._reducer._ensure_plan()
        assert len(plan.buckets) >= 4
        cap = int(0.02 * 1024 * 1024)
        for b in plan.buckets:
            assert b.numel * np.dtype(b.dtype).itemsize <= max(
                cap, max(b.sizes) * np.dtype(b.dtype).itemsize)
        # every trainable param is in exactly one bucket
        counted = [id(p) for b in plan.buckets for p in b.params]
        assert sorted(counted) == sorted(
            id(p) for p in m.parameters() if not p.stop_gradient)

    def test_last_comm_buffer_tail_split(self):
        paddle.seed(5)
        m = nn.Sequential(*[nn.Linear(64, 64) for _ in range(4)])
        # everything fits one 1 MB bucket; the 0.02 MB tail cap splits off a
        # small straggler bucket holding the FIRST layer's params — the last
        # grads to become final in backward, flushed without waiting for a
        # full-size buffer (reference last_comm_buffer_size_MB semantics)
        d = dist.DataParallel(m, comm_buffer_size_MB=1,
                              last_comm_buffer_size_MB=0.02)
        plan = d._reducer._ensure_plan()
        assert len(plan.buckets) == 2
        tail = plan.buckets[-1]
        assert tail.numel * np.dtype(tail.dtype).itemsize <= int(
            0.02 * 1024 * 1024)
        first_layer = {id(m[0].weight), id(m[0].bias)}
        assert first_layer == {id(p) for p in tail.params}

    def test_dead_prebucket_api_removed(self):
        d = dist.DataParallel(_MLP())
        assert not hasattr(d, "_ensure_buckets")
        assert not hasattr(d, "_buckets")

    def test_zero_rebuild_steady_state(self):
        flags.set_flags({"dp_shard_update": True})
        paddle.seed(9)
        m = _MLP()
        d = dist.DataParallel(m)
        o = opt.Adam(learning_rate=0.05, parameters=m.parameters())
        so = dist.sharded_update(o, d)

        def step(i):
            x = paddle.to_tensor(
                np.random.RandomState(i).randn(8, 8).astype(np.float32))
            d(x).mean().backward()
            so.step()
            so.clear_grad()

        step(0)
        step(1)  # warm: plan built, executables traced, fused jit built
        builds = _metric("paddle_dp_flat_pack_builds_total")
        calls = _metric("paddle_dp_flat_pack_calls_total")
        for i in range(2, 5):
            step(i)
        assert _metric("paddle_dp_flat_pack_builds_total") == builds
        assert _metric("paddle_dp_flat_pack_calls_total") > calls
        flags.set_flags({"dp_shard_update": False})


class TestCommDtype:
    def test_bf16_wire_dtype(self):
        w_ref, _, _ = _train(opt.SGD, shard=False, steps=2)
        flags.set_flags({"dp_grad_comm_dtype": "bf16"})
        try:
            before = _metric("paddle_dp_bytes_reduced_total")
            w_bf, _, d = _train(opt.SGD, shard=True, steps=2)
            reduced = _metric("paddle_dp_bytes_reduced_total") - before
        finally:
            flags.set_flags({"dp_grad_comm_dtype": ""})
        # params stay fp32; update approximates the fp32 trajectory
        for a, b in zip(w_ref, w_bf):
            assert str(b.dtype) == "float32"
            assert np.allclose(a, b, atol=5e-2)
        # the wire moved 2-byte elements: per step, sum of padded*2 bytes
        plan = d._reducer._ensure_plan()
        per_step = sum(b.padded * 2 for b in plan.buckets)
        assert reduced == 2 * per_step

    def test_bad_comm_dtype_rejected(self):
        # int8 is a valid wire since the quant_comm codec; int4 is not
        flags.set_flags({"dp_grad_comm_dtype": "int4"})
        try:
            paddle.seed(3)
            d = dist.DataParallel(_MLP())
            with pytest.raises(ValueError, match="dp_grad_comm_dtype"):
                d(paddle.to_tensor(
                    np.ones((4, 8), np.float32))).mean().backward()
        finally:
            flags.set_flags({"dp_grad_comm_dtype": ""})


class TestChaosDrill:
    def test_watchdog_names_inflight_bucket(self, capfd):
        """Kill one bucket's collective mid-backward: the chaos hook hangs
        the reduce-scatter inside the armed comm_task past the watchdog
        timeout; the warn escalation must name the bucket op."""
        flags.set_flags({"chaos_spec":
                         "collective:hang@op=reduce_scatter_avg;delay=1.0",
                         "comm_timeout": 0.3,
                         "watchdog_policy": "warn",
                         "comm_watchdog_abort": False,
                         "dp_shard_update": True})
        try:
            before = _metric("paddle_watchdog_escalations_total",
                             {"stage": "warn"})
            paddle.seed(3)
            m = _MLP()
            d = dist.DataParallel(m)
            o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
            so = dist.sharded_update(o, d)
            d(paddle.to_tensor(
                np.ones((4, 8), np.float32))).mean().backward()
            so.step()
            assert _metric("paddle_watchdog_escalations_total",
                           {"stage": "warn"}) >= before + 1
            err = capfd.readouterr().err
            assert "stage=warn" in err
            # the escalation names the exact in-flight bucket collective
            assert "dp:reduce_scatter_avg:bucket0" in err
        finally:
            flags.set_flags({"chaos_spec": "", "comm_timeout": 0.0,
                             "watchdog_policy": "",
                             "dp_shard_update": False})

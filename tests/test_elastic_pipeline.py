"""Elastic pipeline parallelism: stage-death detection via TTL leases,
epoch-fenced pipeline runs, bitwise pp-reshard and accumulation-window
replay (distributed/elastic/pipeline.py).

The drills run on the 8-virtual-device CPU mesh (conftest.py) in
single-controller mode: "killing a stage replica" revokes its heartbeat
lease mid-microbatch, which exercises exactly the machinery (fence,
abort at an action boundary, stage-state migration through reshard_pp,
schedule re-validation, window replay) that per-stage controllers need.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import observability as obs
from paddle_tpu.core import flags
from paddle_tpu.distributed.elastic import (ElasticPipelineError,
                                            ElasticPipelineRuntime,
                                            EpochChangedError,
                                            maybe_start_pp)
from paddle_tpu.distributed.elastic import epoch as ep
from paddle_tpu.distributed.fault_tolerance import chaos
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers import (
    pp_layers)
from paddle_tpu.distributed.pipeline import PipelineEngine
from paddle_tpu.distributed.pipeline import runtime as pp_runtime

pytestmark = pytest.mark.chaos

L, H, M = 4, 8, 4


@pytest.fixture(autouse=True)
def _isolation():
    """No chaos spec, guard, kill hook, or epoch bump may leak."""
    yield
    chaos.reconfigure("")
    chaos.set_rank_kill_hook(None)
    pp_runtime.set_elastic_guard(None)
    flags.set_flags({"elastic_pp": False})
    if ep.current() != 0:
        ep._reset_for_tests()


def _mse(out, label):
    return ((out - label) ** 2).mean()


def _factory(pp):
    descs = []
    for _ in range(L):
        descs.append(pp_layers.LayerDesc(nn.Linear, H, H))
        descs.append(pp_layers.LayerDesc(nn.ReLU))
    model = pp_layers.PipelineLayer(layers=descs, loss_fn=_mse,
                                    num_stages=pp)
    rs = np.random.RandomState(0)
    for p in model.parameters():
        p.set_value(paddle.to_tensor(
            rs.normal(scale=0.2, size=p.shape).astype(np.float32)))
    engine = PipelineEngine(model, accumulate_steps=M)
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    return engine, opt


def _batch(seed):
    rs = np.random.RandomState(seed)
    x = paddle.to_tensor(rs.normal(size=(M, H)).astype(np.float32))
    y = paddle.to_tensor(rs.normal(size=(M, H)).astype(np.float32))
    return x, y


def _step(ert, seed):
    x, y = _batch(seed)
    loss = ert.run(x, y, train=True)
    ert.optimizer.step()
    ert.optimizer.clear_grad()
    return float(np.asarray(loss._data))


def _metric(name, labels=None):
    return obs.registry().value(name, labels or {})


def test_stage_death_drill_reconfigures_once_and_training_continues():
    """The acceptance drill (tools/elastic_pp_smoke.py runs the 4-stage
    version as a CI gate): chaos drops a stage dead mid-1F1B; exactly one
    reconfigure is asserted from the metrics registry, and the survivors
    keep training at the shrunken degree."""
    ert = ElasticPipelineRuntime(_factory, 2).start()
    rc0 = _metric("paddle_elastic_events_total", {"kind": "reconfigure"})
    sd0 = _metric("paddle_elastic_events_total", {"kind": "stage_dead"})
    try:
        losses = [_step(ert, seed=0)]
        chaos.reconfigure("pipeline:rank_dead@stage=1;count=1")
        losses += [_step(ert, seed=i) for i in (1, 2)]
    finally:
        chaos.reconfigure("")
        ert.stop()
    assert _metric("paddle_elastic_events_total",
                   {"kind": "reconfigure"}) - rc0 == 1
    assert _metric("paddle_elastic_events_total",
                   {"kind": "stage_dead"}) - sd0 == 1
    assert ert.engine.P_phys == 1          # 4 layers, 1 survivor
    assert ert.reconfigurations == 1 and ert.replays == 1
    assert all(np.isfinite(l) for l in losses)


def test_planned_reshard_to_is_bitwise_params_and_optimizer_state():
    """reshard_to re-partitions the live stack through reshard_pp: every
    param AND every Adam accumulator must land bit-equal (in flattened
    layer order) on the new stages, and the step count must carry."""
    ert = ElasticPipelineRuntime(_factory, 2).start()
    try:
        for i in range(2):
            _step(ert, seed=i)

        def flat(engine, opt):
            inner = getattr(opt, "inner", opt)
            ps, accs = [], []
            for st in engine.stages:
                for p in st.params:
                    ps.append(np.asarray(p._data).copy())
                    accs.append({k: np.asarray(v).copy() for k, v in
                                 inner._accumulators[p.name].items()})
            return ps, accs, inner._step_count

        ps0, accs0, step0 = flat(ert.engine, ert.optimizer)
        assert step0 == 2 and accs0 and all(a for a in accs0)
        ert.reshard_to(1)
        assert ert.engine.P_phys == 1
        ps1, accs1, step1 = flat(ert.engine, ert.optimizer)
        assert step1 == step0
        assert len(ps0) == len(ps1)
        for a, b in zip(ps0, ps1):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(accs0, accs1):
            assert sorted(a) == sorted(b)
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])
        # and the swapped-in optimizer still drives training
        assert np.isfinite(_step(ert, seed=2))
    finally:
        ert.stop()


def test_epoch_bump_mid_run_raises_instead_of_hanging():
    """The fence itself: every dispatch and P2P hop re-checks the run's
    epoch stamp, so a world change lands as EpochChangedError at an
    action boundary — never a hang on a dead stage's buffers."""
    engine, _ = _factory(2)
    x, y = _batch(0)
    fired = [0]

    def bump_once(phase, stage, microbatch):
        if fired[0] == 3:
            ep.bump()
        fired[0] += 1

    prev = pp_runtime.set_elastic_guard(bump_once)
    try:
        with pytest.raises(EpochChangedError, match="pipeline"):
            engine.run(x, y, train=True)
    finally:
        pp_runtime.set_elastic_guard(prev)
    assert fired[0] >= 4


def test_refuses_heterogeneous_stack():
    """Elastic pp reshards through the stage-stacked blocks layout, which
    only exists for homogeneous repeating blocks — a mixed stack must be
    refused at construction, before any failure."""

    def bad_factory(pp):
        descs = [pp_layers.LayerDesc(nn.Linear, H, 2 * H),
                 pp_layers.LayerDesc(nn.Linear, 2 * H, H)]
        model = pp_layers.PipelineLayer(layers=descs, loss_fn=_mse,
                                        num_stages=pp)
        return PipelineEngine(model, accumulate_steps=M)

    with pytest.raises(ElasticPipelineError, match="homogeneous|identical"):
        ElasticPipelineRuntime(bad_factory, 2)


def test_maybe_start_pp_gated_on_flag():
    assert maybe_start_pp(_factory, 2) is None
    flags.set_flags({"elastic_pp": True})
    ert = maybe_start_pp(_factory, 2)
    try:
        assert isinstance(ert, ElasticPipelineRuntime)
        assert ert.engine.P_phys == 2
    finally:
        ert.stop()
        flags.set_flags({"elastic_pp": False})


def test_no_feasible_degree_refuses_and_raises():
    """min_pp above the surviving degree: the runtime must refuse (with a
    metric) rather than silently train a mis-partitioned model."""
    ert = ElasticPipelineRuntime(_factory, 2, min_pp=2).start()
    rf0 = _metric("paddle_elastic_events_total", {"kind": "refuse"})
    try:
        _step(ert, seed=0)
        chaos.reconfigure("pipeline:rank_dead@stage=0;count=1")
        with pytest.raises(ElasticPipelineError, match="feasible"):
            _step(ert, seed=1)
    finally:
        chaos.reconfigure("")
        ert.stop()
    assert _metric("paddle_elastic_events_total",
                   {"kind": "refuse"}) - rf0 == 1

"""yolo_loss and hsigmoid_loss vs independent numpy transcriptions.

The numpy references below re-implement the reference algorithms
(cpu/yolo_loss_kernel.cc and funcs/matrix_bit_code.h SimpleCode) directly
from their scalar loops, so the dense/vmapped jnp kernels are checked
against a structurally different implementation.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _C_ops

RS = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _bce(x, l):
    return max(x, 0.0) - x * l + math.log1p(math.exp(-abs(x)))


def _iou_xywh(b1, b2):
    lo = np.maximum(b1[:2] - b1[2:] / 2, b2[:2] - b2[2:] / 2)
    hi = np.minimum(b1[:2] + b1[2:] / 2, b2[:2] + b2[2:] / 2)
    wh = hi - lo
    inter = wh[0] * wh[1] if (wh > 0).all() else 0.0
    union = b1[2] * b1[3] + b2[2] * b2[3] - inter
    return inter / max(union, 1e-10)


def _np_yolo_loss(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                  class_num, ignore_thresh, downsample_ratio,
                  use_label_smooth=True, scale_x_y=1.0):
    N, _, H, W = x.shape
    an_num = len(anchors) // 2
    mask_num = len(anchor_mask)
    B = gt_box.shape[1]
    input_size = downsample_ratio * H
    sc, bi = scale_x_y, -0.5 * (scale_x_y - 1.0)
    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40)
        pos_l, neg_l = 1.0 - sw, sw
    else:
        pos_l, neg_l = 1.0, 0.0
    sig = lambda v: 1.0 / (1.0 + math.exp(-v))
    loss = np.zeros(N)
    objm = np.zeros((N, mask_num, H, W))
    match = -np.ones((N, B), np.int32)
    for i in range(N):
        xr = x[i].reshape(mask_num, 5 + class_num, H, W)
        valid = [(gt_box[i, t, 2] > 1e-6 and gt_box[i, t, 3] > 1e-6)
                 for t in range(B)]
        for j in range(mask_num):
            for k in range(H):
                for l in range(W):
                    an = anchor_mask[j]
                    pred = np.array([
                        (l + sig(xr[j, 0, k, l]) * sc + bi) / W,
                        (k + sig(xr[j, 1, k, l]) * sc + bi) / H,
                        math.exp(xr[j, 2, k, l]) * anchors[2 * an]
                        / input_size,
                        math.exp(xr[j, 3, k, l]) * anchors[2 * an + 1]
                        / input_size])
                    best = 0.0
                    for t in range(B):
                        if valid[t]:
                            best = max(best, _iou_xywh(pred, gt_box[i, t]))
                    if best > ignore_thresh:
                        objm[i, j, k, l] = -1.0
        for t in range(B):
            if not valid[t]:
                continue
            gt = gt_box[i, t]
            gi, gj = int(gt[0] * W), int(gt[1] * H)
            best_iou, best_n = 0.0, 0
            for an in range(an_num):
                an_box = np.array([0, 0, anchors[2 * an] / input_size,
                                   anchors[2 * an + 1] / input_size])
                iou = _iou_xywh(an_box, np.array([0, 0, gt[2], gt[3]]))
                if iou > best_iou:
                    best_iou, best_n = iou, an
            mask_idx = anchor_mask.index(best_n) \
                if best_n in anchor_mask else -1
            match[i, t] = mask_idx
            if mask_idx < 0:
                continue
            score = gt_score[i, t]
            tx = gt[0] * W - gi
            ty = gt[1] * H - gj
            tw = math.log(gt[2] * input_size / anchors[2 * best_n])
            th = math.log(gt[3] * input_size / anchors[2 * best_n + 1])
            s = (2.0 - gt[2] * gt[3]) * score
            loss[i] += _bce(xr[mask_idx, 0, gj, gi], tx) * s
            loss[i] += _bce(xr[mask_idx, 1, gj, gi], ty) * s
            loss[i] += abs(tw - xr[mask_idx, 2, gj, gi]) * s
            loss[i] += abs(th - xr[mask_idx, 3, gj, gi]) * s
            objm[i, mask_idx, gj, gi] = score
            for c in range(class_num):
                tgt = pos_l if c == gt_label[i, t] else neg_l
                loss[i] += _bce(xr[mask_idx, 5 + c, gj, gi], tgt) * score
        for j in range(mask_num):
            for k in range(H):
                for l in range(W):
                    o = objm[i, j, k, l]
                    if o > 1e-5:
                        loss[i] += _bce(xr[j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[i] += _bce(xr[j, 4, k, l], 0.0)
    return loss, objm, match


def _yolo_case(seed=0):
    rs = np.random.RandomState(seed)
    N, H, W, C = 2, 4, 4, 3
    anchors = [10, 14, 23, 27, 37, 58]
    anchor_mask = [1, 2]
    x = rs.randn(N, len(anchor_mask) * (5 + C), H, W).astype(np.float32)
    gt = rs.uniform(0.2, 0.8, (N, 3, 4)).astype(np.float32) * \
        np.array([1, 1, 0.4, 0.4], np.float32)
    gt[0, 2] = 0.0  # invalid slot
    lab = rs.randint(0, C, (N, 3)).astype(np.int32)
    score = rs.uniform(0.5, 1.0, (N, 3)).astype(np.float32)
    return x, gt, lab, score, anchors, anchor_mask, C


def test_yolo_loss_matches_numpy_reference():
    x, gt, lab, score, anchors, mask, C = _yolo_case()
    loss, objm, match = _C_ops.yolo_loss(
        _t(x), _t(gt), _t(lab), _t(score), anchors=anchors,
        anchor_mask=mask, class_num=C, ignore_thresh=0.5,
        downsample_ratio=32)
    wl, wo, wm = _np_yolo_loss(x.astype(np.float64), gt, lab, score,
                               anchors, mask, C, 0.5, 32)
    np.testing.assert_allclose(loss.numpy(), wl, rtol=1e-4)
    np.testing.assert_allclose(objm.numpy(), wo, atol=1e-6)
    np.testing.assert_allclose(match.numpy(), wm)


def test_yolo_loss_gradient_flows():
    x, gt, lab, score, anchors, mask, C = _yolo_case(1)
    xt = _t(x)
    xt.stop_gradient = False
    loss, _, _ = _C_ops.yolo_loss(xt, _t(gt), _t(lab), _t(score),
                                  anchors=anchors, anchor_mask=mask,
                                  class_num=C, ignore_thresh=0.5,
                                  downsample_ratio=32)
    loss.sum().backward()
    g = xt.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def _np_hsigmoid(x, label, num_classes, weight, bias):
    N = x.shape[0]
    loss = np.zeros((N, 1))
    for n in range(N):
        c = int(label[n]) + num_classes
        length = int(math.floor(math.log2(c)))
        for bit in range(length):
            idx = (c >> (bit + 1)) - 1
            tgt = float((c >> bit) & 1)
            pre = float(weight[idx] @ x[n] + (bias[idx] if bias is not None
                                              else 0.0))
            pre = max(-40.0, min(40.0, pre))
            loss[n, 0] += _bce(pre, tgt)
    return loss


def test_hsigmoid_matches_numpy_reference():
    N, D, C = 5, 8, 7
    x = RS.randn(N, D).astype(np.float32)
    lab = RS.randint(0, C, N).astype(np.int64)
    w = RS.randn(C - 1, D).astype(np.float32) * 0.3
    b = RS.randn(C - 1).astype(np.float32) * 0.1
    loss, pre = _C_ops.hsigmoid_loss(_t(x), _t(lab), C, _t(w), _t(b))
    want = _np_hsigmoid(x.astype(np.float64), lab, C, w, b)
    np.testing.assert_allclose(loss.numpy(), want, rtol=1e-4)


def test_hsigmoid_gradient_and_custom_tree_gate():
    N, D, C = 4, 6, 10
    x = _t(RS.randn(N, D).astype(np.float32))
    x.stop_gradient = False
    w = _t((RS.randn(C - 1, D) * 0.3).astype(np.float32))
    w.stop_gradient = False
    loss, _ = _C_ops.hsigmoid_loss(x, _t(RS.randint(0, C, N)), C, w)
    loss.sum().backward()
    assert np.abs(x.grad.numpy()).sum() > 0
    assert np.abs(w.grad.numpy()).sum() > 0
    with pytest.raises(NotImplementedError, match="custom tree"):
        _C_ops.hsigmoid_loss(x, _t(RS.randint(0, C, N)), C, w,
                             path_table=_t(np.zeros((N, 2))))


def test_yolo_loss_padded_slot_does_not_clobber_objectness():
    """Review repro: an invalid (all-zero) gt slot's garbage assignment
    indices must not overwrite a real gt's objectness score."""
    C = 3
    anchors = [10, 14, 23, 27]
    mask = [0, 1]
    x = np.zeros((1, 2 * (5 + C), 4, 4), np.float32)
    gt = np.zeros((1, 2, 4), np.float32)
    gt[0, 0] = [0.1, 0.1, 0.2, 0.2]     # valid: assigned near cell (0,0)
    lab = np.zeros((1, 2), np.int32)
    score = np.full((1, 2), 0.9, np.float32)
    loss, objm, match = _C_ops.yolo_loss(
        _t(x), _t(gt), _t(lab), _t(score), anchors=anchors,
        anchor_mask=mask, class_num=C, ignore_thresh=0.5,
        downsample_ratio=32)
    m = int(match.numpy()[0, 0])
    assert m >= 0 and int(match.numpy()[0, 1]) == -1
    assert objm.numpy()[0, m, 0, 0] == pytest.approx(0.9)
    wl, wo, wm = _np_yolo_loss(x.astype(np.float64), gt, lab, score,
                               anchors, mask, C, 0.5, 32)
    np.testing.assert_allclose(loss.numpy(), wl, rtol=1e-4)
    np.testing.assert_allclose(objm.numpy(), wo, atol=1e-6)

"""DataLoader shared-memory worker transport (r4 VERDICT Next #7).

The native SPSC ShmRing (core/native) is now the worker→parent batch
channel when use_shared_memory=True — the analog of the reference's mmap
worker transfer (python/paddle/io/dataloader/dataloader_iter.py). These
tests run REAL spawned workers over the ring, assert parity with the
mp.Queue path, exercise in-band worker errors and oversized batches, and
record the transport-time comparison.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import native
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native lib unavailable")


class ArrayDataset(Dataset):
    def __init__(self, n=64, shape=(3, 16, 16), seed=0):
        self.x = np.random.RandomState(seed).rand(n, *shape).astype(
            np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], np.int64(i % 10)


class FailingDataset(ArrayDataset):
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("poisoned sample")
        return super().__getitem__(i)


def _collect(loader):
    out = []
    for xb, yb in loader:
        out.append((np.asarray(xb.numpy()), np.asarray(yb.numpy())))
    return out


@needs_native
def test_ring_transport_active_and_parity():
    ds = ArrayDataset()
    shm = DataLoader(ds, batch_size=8, num_workers=2,
                     use_shared_memory=True)
    it = iter(shm)
    inner = it._inner  # _TimedIter wraps the multiprocess iter
    assert inner._ring_active, "native path should be active"
    got_shm = [(x.copy(), y.copy()) for x, y in _iter_np(it)]
    q = DataLoader(ds, batch_size=8, num_workers=2, use_shared_memory=False)
    got_q = _collect(q)
    assert len(got_shm) == len(got_q) == 8
    for (xa, ya), (xb, yb) in zip(got_shm, got_q):
        np.testing.assert_allclose(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def _iter_np(it):
    for xb, yb in it:
        yield np.asarray(xb.numpy()), np.asarray(yb.numpy())


@needs_native
def test_ring_worker_error_propagates():
    dl = DataLoader(FailingDataset(), batch_size=4, num_workers=2,
                    use_shared_memory=True)
    with pytest.raises(RuntimeError, match="poisoned sample"):
        _collect(dl)


def test_queue_fallback_when_disabled():
    ds = ArrayDataset(n=16)
    dl = DataLoader(ds, batch_size=4, num_workers=2,
                    use_shared_memory=False)
    it = iter(dl)
    assert not it._inner._ring_active
    assert len(list(_iter_np(it))) == 4


@needs_native
def test_large_batch_transport():
    """Multi-megabyte batches flow through the ring (chunked pop path)."""
    ds = ArrayDataset(n=8, shape=(3, 128, 128))
    dl = DataLoader(ds, batch_size=4, num_workers=1,
                    use_shared_memory=True)
    batches = list(_iter_np(iter(dl)))
    assert batches[0][0].shape == (4, 3, 128, 128)


@needs_native
def test_transport_timing_recorded():
    """reader-side wall time for ~100 MB through each transport; the ring
    must at least be in the same league (hard bound is loose — CI noise),
    and the measured ratio is printed for the bench record."""
    ds = ArrayDataset(n=96, shape=(3, 224, 224))  # ~57 MB total

    def run(use_shm):
        dl = DataLoader(ds, batch_size=16, num_workers=2,
                        use_shared_memory=use_shm)
        t0 = time.perf_counter()
        n = sum(1 for _ in _iter_np(iter(dl)))
        assert n == 6
        return time.perf_counter() - t0

    run(False)  # warm spawn caches
    t_q = min(run(False) for _ in range(2))
    t_ring = min(run(True) for _ in range(2))
    print(f"\n[shm-ring] queue={t_q:.3f}s ring={t_ring:.3f}s "
          f"ratio={t_ring / t_q:.2f}")
    assert t_ring < 3.0 * t_q


class BigDataset(Dataset):
    """12 MB/sample -> a 96 MB batch exceeds the 64 MB default ring."""

    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.full((3, 1024, 1024), float(i), np.float32)


@needs_native
def test_oversized_batch_falls_back_to_queue():
    """A batch bigger than the ring capacity must still arrive (mp.Queue
    fallback for that batch), not abort the iteration."""
    dl = DataLoader(BigDataset(), batch_size=8, num_workers=1,
                    use_shared_memory=True)
    batches = [np.asarray(b.numpy()) for b in iter(dl)]
    assert batches[0].shape == (8, 3, 1024, 1024)
    np.testing.assert_allclose(batches[0][3, 0, 0, 0], 3.0)

"""Mosaic-lowering CI smoke: lower the Pallas flash kernels FOR TPU on CPU.

VERDICT r3 Weak #8 / task #9: all flash tests run interpret=True, so a
Mosaic legalization regression (like the r02 lse BlockSpec or the int64
index-map bug) only surfaced at bench time on the chip. `jax.export` with
platforms=['tpu'] runs the REAL Mosaic lowering pipeline
(`pallas_call_tpu_lowering_rule` -> `lower_jaxpr_to_module`, including
`_check_block_mappings`) without TPU hardware, so BlockSpec/legalization
bugs fail here in CPU CI instead.

Reference analog: the compile-only coverage the reference gets from
`paddle/phi/kernels/gpu/flash_attn_kernel.cc` building in CI even on
CUDA-less machines.
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas import flash_attention as fa


def _export_tpu(fn, *args):
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _sds(shape, dtype=jnp.bfloat16):
    return jax.ShapeDtypeStruct(shape, dtype)


def test_flash_fwd_lowers_for_tpu():
    q = _sds((2, 4, 256, 64))
    fn = lambda q, k, v: fa._flash_bhtd(q, k, v, 0.125, True, False)
    exported = _export_tpu(fn, q, q, q)
    assert "tpu_custom_call" in exported.mlir_module()


def test_flash_fwd_bwd_lowers_for_tpu():
    """The full custom_vjp pair — fwd, dq, and dkv kernels — all legalize."""
    q = _sds((2, 4, 256, 64))

    def loss(q, k, v):
        o = fa._flash_bhtd(q, k, v, 0.125, True, False)
        return jnp.sum(o.astype(jnp.float32))

    exported = _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, q, q)
    # fwd (re-run) + dq + dkv pallas calls all present
    assert exported.mlir_module().count("tpu_custom_call") >= 3


def test_flash_gqa_lowers_for_tpu():
    """GQA index maps (h // group with lax.div on int32) legalize."""
    q = _sds((2, 8, 256, 64))
    kv = _sds((2, 2, 256, 64))

    def loss(q, k, v):
        o = fa._flash_bhtd(q, k, v, 0.125, True, False)
        return jnp.sum(o.astype(jnp.float32))

    _export_tpu(jax.grad(loss, argnums=(0, 1, 2)), q, kv, kv)


def test_flash_bench_shape_lowers_for_tpu():
    """The flagship bench shape (block 512 path, bf16)."""
    q = _sds((1, 12, 2048, 128))

    def step(q, k, v):
        o = fa._flash_bhtd(q, k, v, 0.088, True, False)
        return jnp.sum(o.astype(jnp.float32))

    _export_tpu(jax.value_and_grad(step, argnums=(0, 1, 2)), q, q, q)


def test_r02_lse_blockspec_fails_tpu_lowering():
    """Deliberately rebuild the r02 bug — a rank-3 lse output whose block
    (1, 1, bq) puts a size-1 second-minor dim against H — and prove the
    TPU export harness catches it WITHOUT hardware. This guards the guard:
    if jax.export ever stops running Mosaic's block-mapping check, this
    test fails and the smoke above is known to be toothless."""
    B, H, T, bq = 2, 4, 512, 256

    def kernel(x_ref, o_ref):
        o_ref[0, 0] = jnp.max(x_ref[0, 0], axis=-1)

    def bad(x):
        return pl.pallas_call(
            kernel,
            grid=(B, H, T // bq),
            in_specs=[pl.BlockSpec((1, 1, bq, 128),
                                   lambda b, h, i: (b, h, i, np.int32(0)))],
            out_specs=pl.BlockSpec((1, 1, bq),
                                   lambda b, h, i: (b, h, i)),
            out_shape=jax.ShapeDtypeStruct((B, H, T), jnp.float32),
        )(x)

    x = _sds((B, H, T, 128), jnp.float32)
    with pytest.raises(Exception, match="divisible|block shape"):
        _export_tpu(bad, x)


def test_static_mirror_agrees_with_mosaic():
    """The CPU-side `_assert_mosaic_tileable` mirror rejects exactly the
    r02 spec too, so interpret-mode tests fail fast as well."""
    with pytest.raises(ValueError, match="tiling rule"):
        fa._assert_mosaic_tileable((1, 1, 256), (2, 4, 512), "lse output")
    # legal: trailing dim equals array dim
    fa._assert_mosaic_tileable((1, 1, 256, fa.LANES), (2, 4, 512, fa.LANES),
                               "lse output")

"""Inference C API end-to-end tests.

Builds libpaddle_tpu_c.so (g++, cached) and drives it exactly the way a C
deployment client would — via the C ABI declared in
paddle_tpu/inference/capi/paddle_c_api.h — against a jit-saved model. The
ctypes layer here stands in for the C consumer; the worker process, socket
protocol, and output-ownership contract are all exercised for real.
Reference analog: paddle/fluid/inference/capi_exp (C API over
AnalysisPredictor).
"""
from __future__ import annotations

import ctypes
import os
import shutil
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def capi():
    from paddle_tpu.inference import capi as capi_mod

    os.environ["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + os.environ.get("PYTHONPATH", ""))
    return capi_mod.load()


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    m.eval()
    path = str(tmp_path_factory.mktemp("capi") / "inference" / "model")
    paddle.jit.save(m, path,
                    input_spec=[paddle.static.InputSpec([1, 8], "float32")])
    return m, path + ".pdmodel"


def _make_predictor(capi, model_file):
    cfg = capi.PD_ConfigCreate()
    capi.PD_ConfigSetModel(cfg, model_file.encode())
    capi.PD_ConfigSetDevice(cfg, b"cpu")
    capi.PD_ConfigSetPythonExe(cfg, sys.executable.encode())
    capi.PD_ConfigSetStartupTimeout(cfg, 300)
    pred = capi.PD_PredictorCreate(cfg)
    capi.PD_ConfigDestroy(cfg)
    return pred


def _run_once(capi, pred, name, x):
    shape = (ctypes.c_int64 * x.ndim)(*x.shape)
    rc = capi.PD_PredictorSetInput(
        pred, name, 0, shape, x.ndim,
        x.ctypes.data_as(ctypes.c_void_p))
    assert rc == 0
    assert capi.PD_PredictorRun(pred) == 0, capi.PD_GetLastError()


def _fetch(capi, pred, name):
    dtype = ctypes.c_int()
    ndim = ctypes.c_int()
    shape = (ctypes.c_int64 * 16)()
    data = ctypes.c_void_p()
    rc = capi.PD_PredictorGetOutput(pred, name, ctypes.byref(dtype), shape,
                                    ctypes.byref(ndim), ctypes.byref(data))
    assert rc == 0, capi.PD_GetLastError()
    dims = [shape[i] for i in range(ndim.value)]
    n = int(np.prod(dims)) if dims else 1
    from paddle_tpu.inference.capi import ENUM_TO_DTYPE

    np_dtype = ENUM_TO_DTYPE[dtype.value]
    buf = ctypes.cast(
        data, ctypes.POINTER(ctypes.c_char * (n * np.dtype(np_dtype).itemsize)))
    return np.frombuffer(buf.contents, dtype=np_dtype).reshape(dims).copy()


class TestCApiEndToEnd:
    def test_full_lifecycle_matches_in_process(self, capi, saved_model):
        m, model_file = saved_model
        pred = _make_predictor(capi, model_file)
        assert pred, capi.PD_GetLastError()
        try:
            n_in = capi.PD_PredictorGetInputNum(pred)
            n_out = capi.PD_PredictorGetOutputNum(pred)
            assert n_in >= 1 and n_out >= 1
            in_name = capi.PD_PredictorGetInputName(pred, 0)
            out_name = capi.PD_PredictorGetOutputName(pred, 0)

            rs = np.random.RandomState(0)
            x = rs.normal(size=(1, 8)).astype(np.float32)
            _run_once(capi, pred, in_name, x)
            got = _fetch(capi, pred, out_name)
            ref = m(paddle.to_tensor(x)).numpy()
            np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

            # second run through the SAME worker: new inputs, new outputs
            x2 = rs.normal(size=(1, 8)).astype(np.float32)
            _run_once(capi, pred, in_name, x2)
            got2 = _fetch(capi, pred, out_name)
            ref2 = m(paddle.to_tensor(x2)).numpy()
            np.testing.assert_allclose(got2, ref2, rtol=1e-4, atol=1e-5)
            assert not np.allclose(got, got2)
        finally:
            capi.PD_PredictorDestroy(pred)

    def test_bad_output_name_reports_error(self, capi, saved_model):
        _, model_file = saved_model
        pred = _make_predictor(capi, model_file)
        assert pred, capi.PD_GetLastError()
        try:
            in_name = capi.PD_PredictorGetInputName(pred, 0)
            x = np.zeros((1, 8), np.float32)
            _run_once(capi, pred, in_name, x)
            dtype = ctypes.c_int()
            ndim = ctypes.c_int()
            shape = (ctypes.c_int64 * 16)()
            data = ctypes.c_void_p()
            rc = capi.PD_PredictorGetOutput(
                pred, b"no_such_output", ctypes.byref(dtype), shape,
                ctypes.byref(ndim), ctypes.byref(data))
            assert rc != 0
            assert b"no_such_output" in capi.PD_GetLastError()
        finally:
            capi.PD_PredictorDestroy(pred)

    def test_create_with_missing_model_fails(self, capi, tmp_path):
        cfg = capi.PD_ConfigCreate()
        capi.PD_ConfigSetModel(cfg, str(tmp_path / "nope.pdmodel").encode())
        capi.PD_ConfigSetDevice(cfg, b"cpu")
        capi.PD_ConfigSetPythonExe(cfg, sys.executable.encode())
        capi.PD_ConfigSetStartupTimeout(cfg, 60)
        pred = capi.PD_PredictorCreate(cfg)
        capi.PD_ConfigDestroy(cfg)
        assert not pred
        assert capi.PD_GetLastError()

    def test_version_string(self, capi):
        assert b"paddle_tpu" in capi.PD_GetVersion()

"""Serving/decode attention family vs naive reference implementations.

Covers masked_multihead_attention_ (dense-cache decode),
block_multihead_attention_ (paged cache, prefill + decode),
flash_attn_unpadded (varlen packed, pallas segment path + XLA fallback),
variable_length_memory_efficient_attention, fused_multi_transformer_
(prefill/decode consistency). Reference semantics transcribed from the
docstring example of
python/paddle/incubate/nn/functional/block_multihead_attention.py
(naive_attention_impl) — behavior, not code.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.kernels import serving_attention as SA


def naive_sdpa(q, k, v, causal_from=None):
    """q [B,H,T,hd] k/v [B,H,S,hd]; causal_from: col offset of row 0."""
    hd = q.shape[-1]
    s = np.einsum("bhtd,bhsd->bhts", q.astype(np.float64),
                  k.astype(np.float64)) / np.sqrt(hd)
    if causal_from is not None:
        T, S = s.shape[2], s.shape[3]
        rows = np.arange(T)[:, None] + causal_from
        cols = np.arange(S)[None, :]
        s = np.where((cols <= rows)[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhts,bhsd->bhtd", p, v.astype(np.float64))


class TestMaskedMultiheadAttention:
    def test_decode_step_matches_naive(self):
        rs = np.random.RandomState(0)
        B, H, S, hd = 2, 4, 16, 8
        lens = np.array([5, 9], np.int32)
        cache = np.zeros((2, B, H, S, hd), np.float32)
        hist_k = rs.randn(B, H, S, hd).astype(np.float32)
        hist_v = rs.randn(B, H, S, hd).astype(np.float32)
        for b in range(B):
            cache[0, b, :, :lens[b]] = hist_k[b, :, :lens[b]]
            cache[1, b, :, :lens[b]] = hist_v[b, :, :lens[b]]
        x = rs.randn(B, 3 * H * hd).astype(np.float32)
        out, cache_out = SA.masked_multihead_attention_.__wrapped__(
            jnp.asarray(x), jnp.asarray(cache),
            sequence_lengths=jnp.asarray(lens))
        out = np.asarray(out).reshape(B, H, hd)
        cache_out = np.asarray(cache_out)
        qkv = x.reshape(B, 3, H, hd)
        for b in range(B):
            L = lens[b]
            # new k/v written at index L
            np.testing.assert_allclose(cache_out[0, b, :, L], qkv[b, 1],
                                       rtol=1e-6)
            np.testing.assert_allclose(cache_out[1, b, :, L], qkv[b, 2],
                                       rtol=1e-6)
            # untouched history
            np.testing.assert_allclose(cache_out[0, b, :, :L],
                                       hist_k[b, :, :L], rtol=1e-6)
            k_full = np.concatenate([hist_k[b, :, :L], qkv[b, 1][:, None]], 1)
            v_full = np.concatenate([hist_v[b, :, :L], qkv[b, 2][:, None]], 1)
            ref = naive_sdpa(qkv[b, 0][None, :, None], k_full[None],
                             v_full[None])[0, :, 0]
            np.testing.assert_allclose(out[b], ref, rtol=2e-5, atol=2e-5)

    def test_rotary_and_bias(self):
        rs = np.random.RandomState(1)
        B, H, S, hd = 1, 2, 8, 8
        cache = jnp.zeros((2, B, H, S, hd), jnp.float32)
        x = rs.randn(B, 3 * H * hd).astype(np.float32)
        bias = rs.randn(3, H, hd).astype(np.float32)
        rot = rs.randn(B, 1, 1, S, hd).astype(np.float32)
        out, _ = SA.masked_multihead_attention_.__wrapped__(
            jnp.asarray(x), cache, bias=jnp.asarray(bias),
            sequence_lengths=jnp.zeros((B,), jnp.int32),
            rotary_tensor=jnp.asarray(rot), rotary_emb_dims=1)
        # one token in cache -> softmax over a single position -> out == v+bv
        v = (x.reshape(B, 3, H, hd) + bias[None])[:, 2]
        np.testing.assert_allclose(np.asarray(out).reshape(B, H, hd), v,
                                   rtol=1e-5, atol=1e-5)

    def test_quant_args_raise(self):
        with pytest.raises(NotImplementedError):
            SA.masked_multihead_attention_.__wrapped__(
                jnp.zeros((1, 24)), jnp.zeros((2, 1, 1, 4, 8)),
                qkv_out_scale=jnp.ones((3,)))


class TestFlashAttnUnpadded:
    @pytest.mark.parametrize("causal", [True, False])
    def test_packed_matches_per_sequence(self, causal):
        rs = np.random.RandomState(2)
        lens = [100, 156]           # total 256 -> pallas segment path
        total, H, hd = sum(lens), 4, 64
        q = rs.randn(total, H, hd).astype(np.float32)
        k = rs.randn(total, H, hd).astype(np.float32)
        v = rs.randn(total, H, hd).astype(np.float32)
        cu = np.array([0, 100, 256], np.int32)
        out, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(cu), jnp.asarray(cu), causal=causal)
        out = np.asarray(out)
        start = 0
        for L in lens:
            sl = slice(start, start + L)
            ref = naive_sdpa(q[sl].transpose(1, 0, 2)[None],
                             k[sl].transpose(1, 0, 2)[None],
                             v[sl].transpose(1, 0, 2)[None],
                             causal_from=0 if causal else None)
            np.testing.assert_allclose(out[sl],
                                       ref[0].transpose(1, 0, 2),
                                       rtol=2e-4, atol=2e-4)
            start += L

    def test_xla_fallback_odd_total(self):
        """total=37 defeats the pallas tiling -> masked XLA path."""
        rs = np.random.RandomState(3)
        total, H, hd = 37, 2, 16
        q = rs.randn(total, H, hd).astype(np.float32)
        cu = np.array([0, 20, 37], np.int32)
        out, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
            jnp.asarray(cu), jnp.asarray(cu), causal=True)
        ref = naive_sdpa(q[:20].transpose(1, 0, 2)[None],
                         q[:20].transpose(1, 0, 2)[None],
                         q[:20].transpose(1, 0, 2)[None], causal_from=0)
        np.testing.assert_allclose(np.asarray(out)[:20],
                                   ref[0].transpose(1, 0, 2),
                                   rtol=2e-4, atol=2e-4)

    def test_causal_decode_style_cross_lengths(self):
        """causal varlen with q shorter than cached k must bottom-right
        align (1 new token sees ALL cached keys) — r4 review finding #1."""
        rs = np.random.RandomState(13)
        H, hd, Lk = 2, 16, 10
        q = rs.randn(1, H, hd).astype(np.float32)
        k = rs.randn(Lk, H, hd).astype(np.float32)
        v = rs.randn(Lk, H, hd).astype(np.float32)
        out, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(np.array([0, 1], np.int32)),
            jnp.asarray(np.array([0, Lk], np.int32)), causal=True)
        ref = naive_sdpa(q.transpose(1, 0, 2)[None],
                         k.transpose(1, 0, 2)[None],
                         v.transpose(1, 0, 2)[None])  # full attend
        np.testing.assert_allclose(np.asarray(out)[0],
                                   np.asarray(ref)[0, :, 0],
                                   rtol=2e-5, atol=2e-5)
        # and a 2-seq batch: q lens [1,2] over k lens [5,4]
        q2 = rs.randn(3, H, hd).astype(np.float32)
        k2 = rs.randn(9, H, hd).astype(np.float32)
        v2 = rs.randn(9, H, hd).astype(np.float32)
        cu_q = np.array([0, 1, 3], np.int32)
        cu_k = np.array([0, 5, 9], np.int32)
        out2, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
            jnp.asarray(q2), jnp.asarray(k2), jnp.asarray(v2),
            jnp.asarray(cu_q), jnp.asarray(cu_k), causal=True)
        out2 = np.asarray(out2)
        # seq 0: 1 q token, 5 keys, sees all 5
        ref0 = naive_sdpa(q2[0:1].transpose(1, 0, 2)[None],
                          k2[:5].transpose(1, 0, 2)[None],
                          v2[:5].transpose(1, 0, 2)[None])
        np.testing.assert_allclose(out2[0], ref0[0, :, 0], rtol=2e-5,
                                   atol=2e-5)
        # seq 1: 2 q tokens over 4 keys, bottom-right aligned: q0 sees 3
        ref1 = naive_sdpa(q2[1:3].transpose(1, 0, 2)[None],
                          k2[5:9].transpose(1, 0, 2)[None],
                          v2[5:9].transpose(1, 0, 2)[None],
                          causal_from=4 - 2)
        np.testing.assert_allclose(out2[1:3].transpose(1, 0, 2),
                                   ref1[0], rtol=2e-5, atol=2e-5)

    def test_grad_flows(self):
        rs = np.random.RandomState(4)
        total, H, hd = 256, 2, 64
        q = jnp.asarray(rs.randn(total, H, hd).astype(np.float32))
        cu = jnp.asarray(np.array([0, 128, 256], np.int32))

        def loss(q):
            o, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
                q, q, q, cu, cu, causal=True)
            return jnp.sum(o * o)

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0

    def test_qkvpacked(self):
        rs = np.random.RandomState(5)
        total, KV, hd, G = 256, 2, 64, 2
        qkv = rs.randn(total, G + 2, KV, hd).astype(np.float32)
        cu = jnp.asarray(np.array([0, 256], np.int32))
        out, _, _, _ = SA.flash_attn_varlen_qkvpacked.__wrapped__(
            jnp.asarray(qkv), cu, cu, causal=True)
        assert out.shape == (total, G * KV, hd)
        assert np.isfinite(np.asarray(out)).all()


class TestVariableLengthMEA:
    def test_varlen_batch(self):
        rs = np.random.RandomState(6)
        B, H, T, hd = 2, 2, 8, 16
        q = rs.randn(B, H, T, hd).astype(np.float32)
        k = rs.randn(B, H, T, hd).astype(np.float32)
        v = rs.randn(B, H, T, hd).astype(np.float32)
        lens = np.array([5, 8], np.int32)
        out = SA.variable_length_memory_efficient_attention.__wrapped__(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(lens), jnp.asarray(lens), causal=True)
        out = np.asarray(out)
        for b in range(B):
            L = lens[b]
            ref = naive_sdpa(q[b:b+1, :, :L], k[b:b+1, :, :L],
                             v[b:b+1, :, :L], causal_from=0)
            np.testing.assert_allclose(out[b, :, :L], ref[0], rtol=2e-5,
                                       atol=2e-5)
        # pad rows zeroed
        assert np.abs(out[0, :, lens[0]:]).max() == 0.0


class TestBlockMultiheadAttention:
    def _setup(self, rs, B, lens_past, lens_now, H, KV, hd, bs, nblocks):
        max_blocks = 4
        bt = -np.ones((B, max_blocks), np.int32)
        nxt = 0
        for b in range(B):
            need = -(-(lens_past[b] + lens_now[b]) // bs)
            for j in range(need):
                bt[b, j] = nxt
                nxt += 1
        kc = np.zeros((nblocks, KV, bs, hd), np.float32)
        vc = np.zeros((nblocks, KV, bs, hd), np.float32)
        hist_k = [rs.randn(lens_past[b], KV, hd).astype(np.float32)
                  for b in range(B)]
        hist_v = [rs.randn(lens_past[b], KV, hd).astype(np.float32)
                  for b in range(B)]
        for b in range(B):
            for p in range(lens_past[b]):
                kc[bt[b, p // bs], :, p % bs] = hist_k[b][p]
                vc[bt[b, p // bs], :, p % bs] = hist_v[b][p]
        total = sum(lens_now)
        cu = np.zeros(B + 1, np.int32)
        cu[1:] = np.cumsum(lens_now)
        qkv = rs.randn(total, (H + 2 * KV) * hd).astype(np.float32)
        return bt, kc, vc, hist_k, hist_v, cu, qkv

    def test_prefill_matches_naive(self):
        rs = np.random.RandomState(7)
        B, H, KV, hd, bs = 2, 4, 2, 8, 4
        lens_now = [6, 3]
        bt, kc, vc, _, _, cu, qkv = self._setup(
            rs, B, [0, 0], lens_now, H, KV, hd, bs, nblocks=8)
        out, _, kco, vco = SA.block_multihead_attention_.__wrapped__(
            jnp.asarray(qkv), jnp.asarray(kc), jnp.asarray(vc),
            jnp.asarray(np.array(lens_now, np.int32)),
            jnp.asarray(np.zeros(B, np.int32)),
            jnp.asarray(np.array(lens_now, np.int32)),
            cu_seqlens_q=jnp.asarray(cu), cu_seqlens_k=jnp.asarray(cu),
            block_tables=jnp.asarray(bt), block_size=bs)
        out = np.asarray(out)
        kco, vco = np.asarray(kco), np.asarray(vco)
        start = 0
        for b in range(B):
            L = lens_now[b]
            q3 = qkv[start:start + L, :H * hd].reshape(L, H, hd)
            k3 = qkv[start:start + L, H * hd:(H + KV) * hd].reshape(L, KV, hd)
            v3 = qkv[start:start + L, (H + KV) * hd:].reshape(L, KV, hd)
            # cache pages carry the new k/v
            for p in range(L):
                np.testing.assert_allclose(kco[bt[b, p // bs], :, p % bs],
                                           k3[p], rtol=1e-6)
            kr = np.repeat(k3, H // KV, axis=1)
            vr = np.repeat(v3, H // KV, axis=1)
            ref = naive_sdpa(q3.transpose(1, 0, 2)[None],
                             kr.transpose(1, 0, 2)[None],
                             vr.transpose(1, 0, 2)[None], causal_from=0)
            np.testing.assert_allclose(
                out[start:start + L].reshape(L, H, hd),
                ref[0].transpose(1, 0, 2), rtol=2e-5, atol=2e-5)
            start += L

    def test_decode_matches_naive(self):
        rs = np.random.RandomState(8)
        B, H, KV, hd, bs = 2, 2, 2, 8, 4
        past = [5, 9]
        bt, kc, vc, hist_k, hist_v, cu, qkv = self._setup(
            rs, B, past, [1, 1], H, KV, hd, bs, nblocks=8)
        out, _, kco, vco = SA.block_multihead_attention_.__wrapped__(
            jnp.asarray(qkv), jnp.asarray(kc), jnp.asarray(vc),
            jnp.asarray(np.zeros(B, np.int32)),
            jnp.asarray(np.array(past, np.int32)),
            jnp.asarray(np.ones(B, np.int32)),
            cu_seqlens_q=jnp.asarray(cu), cu_seqlens_k=jnp.asarray(cu),
            block_tables=jnp.asarray(bt), block_size=bs)
        out = np.asarray(out)
        for b in range(B):
            q3 = qkv[b, :H * hd].reshape(1, H, hd)
            k_new = qkv[b, H * hd:(H + KV) * hd].reshape(KV, hd)
            v_new = qkv[b, (H + KV) * hd:].reshape(KV, hd)
            k_full = np.concatenate([hist_k[b], k_new[None]], 0)
            v_full = np.concatenate([hist_v[b], v_new[None]], 0)
            kr = np.repeat(k_full, H // KV, axis=1)
            vr = np.repeat(v_full, H // KV, axis=1)
            ref = naive_sdpa(q3.transpose(1, 0, 2)[None],
                             kr.transpose(1, 0, 2)[None],
                             vr.transpose(1, 0, 2)[None])
            np.testing.assert_allclose(out[b].reshape(H, hd),
                                       ref[0, :, 0], rtol=2e-5, atol=2e-5)

    def test_jit_compiles(self):
        rs = np.random.RandomState(9)
        B, H, KV, hd, bs = 1, 2, 2, 8, 4
        bt, kc, vc, _, _, cu, qkv = self._setup(
            rs, B, [0], [4], H, KV, hd, bs, nblocks=4)

        @jax.jit
        def step(qkv, kc, vc):
            return SA.block_multihead_attention_.__wrapped__(
                qkv, kc, vc, jnp.asarray([4], jnp.int32),
                jnp.asarray([0], jnp.int32), jnp.asarray([4], jnp.int32),
                cu_seqlens_q=jnp.asarray(cu), cu_seqlens_k=jnp.asarray(cu),
                block_tables=jnp.asarray(bt), block_size=bs)

        out, _, _, _ = step(jnp.asarray(qkv), jnp.asarray(kc), jnp.asarray(vc))
        assert np.isfinite(np.asarray(out)).all()


class TestFusedMultiTransformer:
    def _weights(self, rs, L, D, H, hd, F):
        mk = lambda *s: rs.randn(*s).astype(np.float32) * 0.05
        return dict(
            ln_scales=[jnp.asarray(np.ones(D, np.float32))] * L,
            ln_biases=[jnp.asarray(np.zeros(D, np.float32))] * L,
            qkv_weights=[jnp.asarray(mk(3, H, hd, D)) for _ in range(L)],
            qkv_biases=[jnp.asarray(np.zeros((3, H, hd), np.float32))] * L,
            linear_weights=[jnp.asarray(mk(H * hd, D)) for _ in range(L)],
            linear_biases=[jnp.asarray(np.zeros(D, np.float32))] * L,
            ffn_ln_scales=[jnp.asarray(np.ones(D, np.float32))] * L,
            ffn_ln_biases=[jnp.asarray(np.zeros(D, np.float32))] * L,
            ffn1_weights=[jnp.asarray(mk(D, F)) for _ in range(L)],
            ffn1_biases=[jnp.asarray(np.zeros(F, np.float32))] * L,
            ffn2_weights=[jnp.asarray(mk(F, D)) for _ in range(L)],
            ffn2_biases=[jnp.asarray(np.zeros(D, np.float32))] * L,
        )

    def test_prefill_then_decode_consistency(self):
        """Decoding token T through the cache must equal running prefill
        over T+1 tokens — the core serving invariant."""
        rs = np.random.RandomState(10)
        L, D, H, hd, F, B, T, S = 2, 16, 2, 8, 32, 1, 4, 8
        w = self._weights(rs, L, D, H, hd, F)
        x_full = rs.randn(B, T + 1, D).astype(np.float32)
        caches = [jnp.zeros((2, B, H, S, hd), jnp.float32) for _ in range(L)]
        # prefill on first T tokens
        out_pre, caches = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full[:, :T]), cache_kvs=caches, **w)
        # decode token T
        out_dec, _ = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full[:, T:T + 1]), cache_kvs=caches,
            time_step=jnp.asarray(T), **w)
        # full prefill over T+1 tokens
        caches2 = [jnp.zeros((2, B, H, S, hd), jnp.float32) for _ in range(L)]
        out_full, _ = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full), cache_kvs=caches2, **w)
        np.testing.assert_allclose(np.asarray(out_dec)[:, 0],
                                   np.asarray(out_full)[:, T],
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(out_pre),
                                   np.asarray(out_full)[:, :T],
                                   rtol=2e-4, atol=2e-4)

    def test_post_ln_prefill_decode_consistency(self):
        """post-LN mode (pre_layer_norm=False) keeps the serving invariant
        and actually uses ffn_ln (code-review finding r4)."""
        rs = np.random.RandomState(11)
        L, D, H, hd, F, B, T, S = 2, 16, 2, 8, 32, 1, 3, 8
        w = self._weights(rs, L, D, H, hd, F)
        # distinct ffn_ln scales so ignoring them would show up
        w["ffn_ln_scales"] = [jnp.asarray(np.full(D, 1.5, np.float32))] * L
        x_full = rs.randn(B, T + 1, D).astype(np.float32)
        caches = [jnp.zeros((2, B, H, S, hd), jnp.float32) for _ in range(L)]
        _, caches = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full[:, :T]), cache_kvs=caches,
            pre_layer_norm=False, **w)
        out_dec, _ = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full[:, T:T + 1]), cache_kvs=caches,
            time_step=jnp.asarray(T), pre_layer_norm=False, **w)
        caches2 = [jnp.zeros((2, B, H, S, hd), jnp.float32) for _ in range(L)]
        out_full, _ = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full), cache_kvs=caches2, pre_layer_norm=False, **w)
        np.testing.assert_allclose(np.asarray(out_dec)[:, 0],
                                   np.asarray(out_full)[:, T],
                                   rtol=2e-4, atol=2e-4)
        # ffn_ln with scale 1.5 must differ from scale 1.0
        w2 = dict(w, ffn_ln_scales=[jnp.asarray(np.ones(D, np.float32))] * L)
        caches3 = [jnp.zeros((2, B, H, S, hd), jnp.float32) for _ in range(L)]
        out_other, _ = SA.fused_multi_transformer_.__wrapped__(
            jnp.asarray(x_full), cache_kvs=caches3, pre_layer_norm=False, **w2)
        assert np.abs(np.asarray(out_full) - np.asarray(out_other)).max() > 1e-3

    def test_misaligned_packing_falls_back(self):
        """flash_attn_unpadded with equal totals but different boundaries
        must NOT take the fused aligned-segment path (finding r4 #5)."""
        rs = np.random.RandomState(12)
        total, H, hd = 256, 2, 64
        q = jnp.asarray(rs.randn(total, H, hd).astype(np.float32))
        cu_q = jnp.asarray(np.array([0, 100, 256], np.int32))
        cu_k = jnp.asarray(np.array([0, 156, 256], np.int32))
        out, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
            q, q, q, cu_q, cu_k, causal=False)
        # reference: q rows 0..99 attend k rows 0..155 (their "sequence 1")
        ref = naive_sdpa(q[:100].transpose(1, 0, 2)[None],
                         q[:156].transpose(1, 0, 2)[None],
                         q[:156].transpose(1, 0, 2)[None])
        np.testing.assert_allclose(np.asarray(out)[:100],
                                   np.asarray(ref)[0].transpose(1, 0, 2),
                                   rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# entry-validation contract: unsupported args rejected before any compute
# ---------------------------------------------------------------------------

class TestEntryValidation:
    def _varlen(self, total=12, H=2, hd=16):
        rs = np.random.RandomState(11)
        q = jnp.asarray(rs.randn(total, H, hd).astype(np.float32))
        cu = jnp.asarray(np.array([0, 5, total], np.int32))
        return q, cu

    def test_unpadded_attn_mask_rejected_at_entry(self):
        """The attn_mask rejection must fire immediately on BOTH routing
        paths (it used to raise only after the fallback SDPA had run)."""
        q, cu = self._varlen()
        with pytest.raises(NotImplementedError, match="attn_mask"):
            SA.flash_attn_unpadded.__wrapped__(
                q, q, q, cu, cu, attn_mask=jnp.zeros((1, 1, 12, 12)))
        # pallas-aligned shape rejects identically
        q2, cu2 = self._varlen(total=256, H=4, hd=64)
        with pytest.raises(NotImplementedError, match="attn_mask"):
            SA.flash_attn_unpadded.__wrapped__(
                q2, q2, q2, cu2, cu2, causal=True,
                attn_mask=jnp.zeros((1, 1, 256, 256)))

    def test_unpadded_dropout_rejected_at_entry(self):
        q, cu = self._varlen()
        with pytest.raises(NotImplementedError, match="dropout"):
            SA.flash_attn_unpadded.__wrapped__(q, q, q, cu, cu, dropout=0.1)
        # is_test=True disables dropout: accepted
        out, _, _, _ = SA.flash_attn_unpadded.__wrapped__(
            q, q, q, cu, cu, dropout=0.1, is_test=True)
        assert out.shape == q.shape

    def test_qkvpacked_inherits_entry_rejection(self):
        rs = np.random.RandomState(12)
        qkv = jnp.asarray(rs.randn(12, 4, 2, 16).astype(np.float32))
        cu = jnp.asarray(np.array([0, 5, 12], np.int32))
        with pytest.raises(NotImplementedError, match="attn_mask"):
            SA.flash_attn_varlen_qkvpacked.__wrapped__(
                qkv, cu, cu, attn_mask=jnp.zeros((1, 1, 12, 12)))

    def test_varlen_mea_bad_gqa_rejected(self):
        rs = np.random.RandomState(13)
        q = jnp.asarray(rs.randn(1, 4, 6, 16).astype(np.float32))
        kv = jnp.asarray(rs.randn(1, 3, 6, 16).astype(np.float32))
        lens = jnp.asarray(np.array([6], np.int32))
        with pytest.raises(ValueError, match="H % KV"):
            SA.variable_length_memory_efficient_attention.__wrapped__(
                q, kv, kv, lens, lens)

    def test_varlen_mea_pre_cache_needs_causal(self):
        rs = np.random.RandomState(14)
        q = jnp.asarray(rs.randn(1, 2, 4, 16).astype(np.float32))
        kv = jnp.asarray(rs.randn(1, 2, 10, 16).astype(np.float32))
        ql = jnp.asarray(np.array([4], np.int32))
        kl = jnp.asarray(np.array([10], np.int32))
        with pytest.raises(NotImplementedError, match="pre_cache_length"):
            SA.variable_length_memory_efficient_attention.__wrapped__(
                q, kv, kv, ql, kl, causal=False, pre_cache_length=6)
        with pytest.raises(ValueError, match=">= 0"):
            SA.variable_length_memory_efficient_attention.__wrapped__(
                q, kv, kv, ql, kl, causal=True, pre_cache_length=-1)
        # the supported form still computes
        out = SA.variable_length_memory_efficient_attention.__wrapped__(
            q, kv, kv, ql, kl, causal=True, pre_cache_length=6)
        assert out.shape == q.shape

"""Generic compiled hybrid engine: dp×pp×tp for arbitrary Layers.

VERDICT r3 task #2 acceptance: a BERT-style model and a non-transformer
model train through dp×pp×tp via fleet with parity vs single-device, with
no model-specific config in the engine's signatures.

Parity caveat baked into the tests: params with mathematically-zero
gradients (conv bias before BN) get ±lr Adam updates from float noise, so
BN-adjacent convs use bias_attr=False (standard practice) — everything
else must match to float tolerance.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.distributed.hybrid import AdamWConfig
from paddle_tpu.distributed.hybrid_generic import (
    GenericHybridEngine, functionalize, generic_tp_specs)
from paddle_tpu.distributed.fleet.meta_parallel.parallel_layers.pp_layers import (
    LayerDesc, PipelineLayer)


def mesh_of(dp, pp, tp):
    n = dp * pp * tp
    return Mesh(np.asarray(jax.devices()[:n]).reshape(dp, pp, tp),
                ("dp", "pp", "tp"))


def ce(out, lab):
    return paddle.nn.functional.cross_entropy(out, lab)


def make_mlp(num_stages=2):
    paddle.seed(0)
    return PipelineLayer([
        LayerDesc(paddle.nn.Linear, 16, 32),
        LayerDesc(paddle.nn.ReLU),
        LayerDesc(paddle.nn.Linear, 32, 32),
        LayerDesc(paddle.nn.ReLU),
        LayerDesc(paddle.nn.Linear, 32, 32),
        LayerDesc(paddle.nn.ReLU),
        LayerDesc(paddle.nn.Linear, 32, 10),
    ], num_stages=num_stages, seg_method="uniform")


def make_convnet(num_stages=2):
    """Non-transformer (conv+BN) pipeline; BN-adjacent convs bias-free."""
    paddle.seed(0)
    return PipelineLayer([
        LayerDesc(paddle.nn.Conv2D, 3, 8, 3, padding=1, bias_attr=False),
        LayerDesc(paddle.nn.BatchNorm2D, 8),
        LayerDesc(paddle.nn.ReLU),
        LayerDesc(paddle.nn.Conv2D, 8, 8, 3, padding=1, bias_attr=False),
        LayerDesc(paddle.nn.BatchNorm2D, 8),
        LayerDesc(paddle.nn.ReLU),
        LayerDesc(paddle.nn.Flatten),
        LayerDesc(paddle.nn.Linear, 8 * 16, 10),
    ], num_stages=num_stages, seg_method="uniform")


class BertBlock(paddle.nn.Layer):
    def __init__(self, d, heads):
        super().__init__()
        self.enc = paddle.nn.TransformerEncoderLayer(d, heads, 4 * d,
                                                     dropout=0.0)

    def forward(self, x):
        return self.enc(x)


class BertEmbed(paddle.nn.Layer):
    def __init__(self, v, t, d):
        super().__init__()
        self.tok = paddle.nn.Embedding(v, d)
        self.pos = paddle.nn.Embedding(t, d)

    def forward(self, tokens):
        T = tokens.shape[1]
        import paddle_tpu as pdl
        pos = pdl.to_tensor(np.arange(T))
        return self.tok(tokens) + self.pos(pos)


class BertHead(paddle.nn.Layer):
    def __init__(self, d, v):
        super().__init__()
        self.fc = paddle.nn.Linear(d, v)

    def forward(self, x):
        return self.fc(x)


def make_bert(num_stages=2, V=64, T=8, D=32, heads=4, L=2):
    paddle.seed(0)
    descs = [LayerDesc(BertEmbed, V, T, D)]
    descs += [LayerDesc(BertBlock, D, heads) for _ in range(L)]
    descs += [LayerDesc(BertHead, D, V)]
    return PipelineLayer(descs, num_stages=num_stages, seg_method="uniform")


def bert_loss(out, lab):
    V = out.shape[-1]
    return paddle.nn.functional.cross_entropy(
        out.reshape([-1, V]), lab.reshape([-1]))


def run_engine(model, mesh, loss_fn, x, y, steps=3, M=1):
    eng = GenericHybridEngine(model, mesh, loss_fn,
                              AdamWConfig(lr=1e-2, weight_decay=0.0),
                              num_microbatches=M)
    return eng, [eng.train_batch(x, y) for _ in range(steps)]


class TestGenericParity:
    def test_mlp_dp2_pp2_tp2(self):
        rs = np.random.RandomState(0)
        x = rs.randn(8, 16).astype(np.float32)
        y = rs.randint(0, 10, (8,))
        _, l1 = run_engine(make_mlp(), mesh_of(1, 1, 1), ce, x, y)
        _, l8 = run_engine(make_mlp(), mesh_of(2, 2, 2), ce, x, y, M=2)
        np.testing.assert_allclose(l1, l8, rtol=2e-4, atol=2e-4)

    def test_convnet_pp2_tp2_with_buffers(self):
        rs = np.random.RandomState(1)
        x = rs.randn(4, 3, 4, 4).astype(np.float32)
        y = rs.randint(0, 10, (4,))
        e1, l1 = run_engine(make_convnet(), mesh_of(1, 1, 1), ce, x, y)
        e4, l4 = run_engine(make_convnet(), mesh_of(1, 2, 2), ce, x, y)
        np.testing.assert_allclose(l1, l4, rtol=2e-4, atol=2e-4)
        # BN running stats thread through the pipeline and match
        assert set(e1.buffers) == set(e4.buffers) and len(e1.buffers) >= 4
        for n in e1.buffers:
            np.testing.assert_allclose(np.asarray(e1.buffers[n]),
                                       np.asarray(e4.buffers[n]),
                                       rtol=1e-4, atol=1e-5)
        # stats actually moved off init
        moved = [n for n in e1.buffers
                 if float(jnp.abs(e1.buffers[n]).max()) > 1e-6]
        assert moved

    def test_bert_dp2_pp2_tp2(self):
        """The BERT bench-config shape through the generic engine."""
        rs = np.random.RandomState(2)
        x = rs.randint(0, 64, (8, 8)).astype(np.int32)
        y = rs.randint(0, 64, (8, 8)).astype(np.int64)
        _, l1 = run_engine(make_bert(), mesh_of(1, 1, 1), bert_loss, x, y)
        _, l8 = run_engine(make_bert(), mesh_of(2, 2, 2), bert_loss, x, y,
                           M=2)
        np.testing.assert_allclose(l1, l8, rtol=3e-4, atol=3e-4)
        assert l1[-1] < l1[0]

    def test_microbatch_invariance_pp(self):
        rs = np.random.RandomState(3)
        x = rs.randn(8, 16).astype(np.float32)
        y = rs.randint(0, 10, (8,))
        _, a = run_engine(make_mlp(), mesh_of(1, 2, 1), ce, x, y, M=1)
        _, b = run_engine(make_mlp(), mesh_of(1, 2, 1), ce, x, y, M=4)
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)

    def test_plain_layer_no_pipeline(self):
        """Any Layer (not a PipelineLayer) works at pp=1."""
        paddle.seed(0)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                     paddle.nn.ReLU(),
                                     paddle.nn.Linear(16, 4))
        rs = np.random.RandomState(4)
        x = rs.randn(8, 8).astype(np.float32)
        y = rs.randint(0, 4, (8,))
        eng = GenericHybridEngine(model, mesh_of(2, 1, 2), ce,
                                  AdamWConfig(lr=1e-2, weight_decay=0.0))
        losses = [eng.train_batch(x, y) for _ in range(4)]
        assert losses[-1] < losses[0]
        # eval and write-back surfaces
        ev = eng.eval_batch(x, y)
        assert np.isfinite(ev)
        eng.sync_to_layer()

    def test_pp_mesh_requires_pipeline_layer(self):
        model = paddle.nn.Linear(4, 4)
        with pytest.raises(ValueError, match="PipelineLayer"):
            GenericHybridEngine(model, mesh_of(1, 2, 1), ce)

    def test_hybrid_make_train_step_dispatches_layers(self):
        """hybrid.make_train_step is model-agnostic: a Layer routes to the
        generic engine (VERDICT r3 task #2 acceptance)."""
        from paddle_tpu.distributed import hybrid as H

        step = H.make_train_step(make_mlp(), mesh_of(1, 2, 2),
                                 num_microbatches=2, loss_fn=ce,
                                 hp=AdamWConfig(lr=1e-2, weight_decay=0.0))
        rs = np.random.RandomState(7)
        x = rs.randn(8, 16).astype(np.float32)
        y = rs.randint(0, 10, (8,))
        losses = [step(x, y) for _ in range(3)]
        assert losses[-1] < losses[0]
        assert step.engine.pp == 2 and step.engine.tp == 2


class TestFunctionalize:
    def test_pure_apply_no_side_effects(self):
        paddle.seed(0)
        layer = paddle.nn.Linear(4, 3)
        apply, params, buffers = functionalize(layer)
        x = np.ones((2, 4), np.float32)
        out, _ = apply(params, buffers, x)
        w0 = layer.weight.numpy().copy()
        params2 = {n: v * 2 for n, v in params.items()}
        out2, _ = apply(params2, buffers, x)
        np.testing.assert_allclose(np.asarray(out2), 2 * np.asarray(out),
                                   rtol=1e-6)
        np.testing.assert_allclose(layer.weight.numpy(), w0)  # restored

    def test_tp_specs_rules(self):
        paddle.seed(0)
        model = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                                     paddle.nn.Linear(16, 8),
                                     paddle.nn.Embedding(10, 8))
        specs = generic_tp_specs(model, tp=2, axis="tp")
        vals = set(map(str, specs.values()))
        # column then row alternation appears
        assert any("'tp'" in s for s in vals)


class TestFleetRouting:
    def test_compiled_flag_routes_to_engine(self):
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2, "compiled": True,
                                   "accumulate_steps": 2}
        fleet.init(is_collective=True, strategy=strategy)
        model = fleet.distributed_model(make_mlp(num_stages=2))
        from paddle_tpu.distributed.fleet.compiled_model import (
            CompiledHybridModel)

        assert isinstance(model, CompiledHybridModel)
        rs = np.random.RandomState(5)
        x = rs.randn(8, 16).astype(np.float32)
        y = rs.randint(0, 10, (8,))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters(),
                                     weight_decay=0.0)
        losses = [float(model.train_batch([x, y], opt, loss_fn=ce).numpy())
                  for _ in range(3)]
        assert losses[-1] < losses[0]
        # parity against the direct single-device engine (betas matching
        # the AdamW optimizer's defaults)
        eng = GenericHybridEngine(
            make_mlp(), mesh_of(1, 1, 1), ce,
            AdamWConfig(lr=1e-2, weight_decay=0.0, beta2=0.999,
                        grad_clip=None))
        ref = [eng.train_batch(x, y) for _ in range(3)]
        np.testing.assert_allclose(losses, ref, rtol=3e-4, atol=3e-4)
        ev = float(model.eval_batch([x, y]).numpy())
        assert np.isfinite(ev)
        sd = model.state_dict()
        assert sd

    def test_lr_schedule_feeds_compiled_step(self):
        """scheduler lr reaches the fused AdamW each step (r4 finding #3):
        an lr=0 schedule must freeze the params."""
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1, "compiled": True}
        fleet.init(is_collective=True, strategy=strategy)
        model = fleet.distributed_model(make_mlp(num_stages=2))
        sched = paddle.optimizer.lr.StepDecay(learning_rate=0.0, step_size=1)
        opt = paddle.optimizer.AdamW(learning_rate=sched,
                                     parameters=model.parameters())
        rs = np.random.RandomState(8)
        x = rs.randn(4, 16).astype(np.float32)
        y = rs.randint(0, 10, (4,))
        l0 = float(model.train_batch([x, y], opt, lr_scheduler=sched,
                                     loss_fn=ce).numpy())
        l1 = float(model.train_batch([x, y], opt, lr_scheduler=sched,
                                     loss_fn=ce).numpy())
        assert l0 == l1  # lr 0 -> nothing moved

    def test_compiled_rejects_unsupported_optimizer(self):
        from paddle_tpu.distributed import fleet

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "compiled": True}
        fleet.init(is_collective=True, strategy=strategy)
        model = fleet.distributed_model(make_mlp(num_stages=2))
        opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                        parameters=model.parameters())
        rs = np.random.RandomState(6)
        with pytest.raises(NotImplementedError, match="AdamW"):
            model.train_batch([rs.randn(4, 16).astype(np.float32),
                               rs.randint(0, 10, (4,))], opt, loss_fn=ce)


def make_uniform_mlp(num_stages=2, width=32):
    """A truly uniform pipeline: every stage is [Linear(w, w), ReLU] — the
    stacked-pp path (r4 VERDICT #6) applies."""
    paddle.seed(0)
    descs = []
    for _ in range(num_stages):
        descs.append(LayerDesc(paddle.nn.Linear, width, width))
        descs.append(LayerDesc(paddle.nn.ReLU))
    return PipelineLayer(descs, num_stages=num_stages, seg_method="uniform")


class TestStackedPP:
    """Uniform stages drop the all-stages lax.switch and shard stage
    params over the pp axis (r4 VERDICT Next #6 acceptance)."""

    def test_uniform_detected_heterogeneous_not(self):
        e_u = GenericHybridEngine(make_uniform_mlp(2), mesh_of(1, 2, 1), ce)
        assert e_u._pp_stacked
        e_h = GenericHybridEngine(make_mlp(2), mesh_of(1, 2, 1), ce)
        assert not e_h._pp_stacked

    def test_per_device_param_bytes_scale_with_pp(self):
        """THE memory claim: each device stores ~total/pp of the stage
        params, not a full replica."""
        pp = 4
        e = GenericHybridEngine(make_uniform_mlp(pp), mesh_of(1, pp, 1), ce)
        total = 0
        local = 0
        for n, arr in e.params.items():
            total += arr.nbytes
            local += arr.addressable_shards[0].data.nbytes
        assert local * pp == total, (local, total)

    def test_uniform_parity_vs_single_device(self):
        rs = np.random.RandomState(11)
        x = rs.randn(8, 32).astype(np.float32)
        y = rs.randint(0, 32, (8,))
        _, l1 = run_engine(make_uniform_mlp(2), mesh_of(1, 1, 1), ce, x, y)
        e2, l2 = run_engine(make_uniform_mlp(2), mesh_of(1, 2, 1), ce, x, y,
                            M=2)
        assert e2._pp_stacked
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-4)

    def test_uniform_parity_dp2_pp2_tp2(self):
        rs = np.random.RandomState(12)
        x = rs.randn(8, 32).astype(np.float32)
        y = rs.randint(0, 32, (8,))
        _, l1 = run_engine(make_uniform_mlp(2), mesh_of(1, 1, 1), ce, x, y)
        e8, l8 = run_engine(make_uniform_mlp(2), mesh_of(2, 2, 2), ce, x, y,
                            M=2)
        assert e8._pp_stacked
        np.testing.assert_allclose(l1, l8, rtol=2e-4, atol=2e-4)

    def test_uniform_with_buffers_parity(self):
        """Per-stage BN buffers live pp-sharded and still match the
        single-device run."""

        def make_bn_pipe(num_stages=2):
            paddle.seed(0)
            descs = []
            for _ in range(num_stages):
                descs.append(LayerDesc(paddle.nn.Linear, 16, 16,
                                       bias_attr=False))
                descs.append(LayerDesc(paddle.nn.BatchNorm1D, 16))
                descs.append(LayerDesc(paddle.nn.ReLU))
            return PipelineLayer(descs, num_stages=num_stages,
                                 seg_method="uniform")

        rs = np.random.RandomState(13)
        x = rs.randn(8, 16).astype(np.float32)
        y = rs.randint(0, 16, (8,))
        e1, l1 = run_engine(make_bn_pipe(2), mesh_of(1, 1, 1), ce, x, y)
        e2, l2 = run_engine(make_bn_pipe(2), mesh_of(1, 2, 1), ce, x, y)
        assert e2._pp_stacked
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-4)
        # compare buffers through the layer view (stacked layout differs)
        e1.sync_to_layer()
        b1 = {n: np.asarray(t.numpy())
              for n, t in e1.model.named_buffers() if t is not None}
        e2.sync_to_layer()
        b2 = {n: np.asarray(t.numpy())
              for n, t in e2.model.named_buffers() if t is not None}
        assert set(b1) == set(b2)
        for n in b1:
            np.testing.assert_allclose(b1[n], b2[n], rtol=1e-4, atol=1e-5)

    def test_tied_params_fall_back(self):
        """A tensor shared across stages forbids stacking."""
        paddle.seed(0)
        shared = paddle.nn.Linear(16, 16)
        model = PipelineLayer([LayerDesc(paddle.nn.ReLU)], num_stages=1)
        # hand-build a 2-stage pipeline sharing one layer object
        model.run_function = [shared, paddle.nn.ReLU(), shared,
                              paddle.nn.ReLU()]
        model._stage_of = [0, 0, 1, 1]
        model._num_stages = 2
        e = GenericHybridEngine.__new__(GenericHybridEngine)
        e._stages = [[shared, model.run_function[1]],
                     [shared, model.run_function[3]]]
        e._param_ts = dict(model.named_parameters())
        e._buffer_ts = {}
        e._detect_uniform_stages()
        assert not e._pp_stacked

    def test_loss_under_cond_keeps_parity(self):
        """The stacked path computes loss inside lax.cond (only the last
        stage's active ticks) so a partial-domain loss_fn never evaluates
        on bubble-tick garbage; this locks grad parity for a log-based
        loss through the cond."""

        def log_loss(out, lab):
            # requires positive inputs — intermediate Linear outputs are not
            p = paddle.nn.functional.softmax(out, axis=-1)
            picked = paddle.sum(
                p * paddle.nn.functional.one_hot(lab, p.shape[-1]), axis=-1)
            return -paddle.mean(paddle.log(picked))

        rs = np.random.RandomState(14)
        x = rs.randn(8, 32).astype(np.float32)
        y = rs.randint(0, 32, (8,))
        _, l1 = run_engine(make_uniform_mlp(2), mesh_of(1, 1, 1), log_loss,
                           x, y)
        e2, l2 = run_engine(make_uniform_mlp(2), mesh_of(1, 2, 1), log_loss,
                            x, y, M=2)
        assert e2._pp_stacked
        assert np.isfinite(l2).all(), l2
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-4)

"""OpTest — the op-unit-test workhorse.

Reference: `test/legacy_test/op_test.py:418` (1189 test files build on it):
run the kernel, compare against a NumPy reference (`check_output`), and
compare analytic gradients against numeric finite differences
(`check_grad`, `get_numeric_gradient` op_test.py:148), across a dtype
matrix with per-op thresholds (the white_list system,
test/white_list/op_accuracy_white_list.py).

TPU-native adaptation: ops are positional-signature registry entries
(paddle_tpu.ops.dispatch.OPS); gradients flow through the eager tape, and
the numeric gradient perturbs inputs through the SAME op call, so the check
covers dispatch + autograd end-to-end.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import OPS

# per-dtype default thresholds (reference: op_threshold_white_list.py)
DTYPE_THRESHOLDS = {
    "float32": dict(rtol=1e-5, atol=1e-6, grad_rtol=5e-3),
    "float64": dict(rtol=1e-7, atol=1e-8, grad_rtol=1e-5),
    "float16": dict(rtol=1e-2, atol=1e-3, grad_rtol=5e-2),
    "bfloat16": dict(rtol=2e-2, atol=2e-2, grad_rtol=1e-1),
}


class OpTest:
    """Subclass contract:
      op_type: registry name
      def setup(self): set self.inputs (list of np arrays), optional
          self.kwargs (dict), and self.np_ref (callable over np arrays).
      optional: dtypes (list), thresholds overrides, grad_inputs (indices).
    """

    op_type: str = ""
    dtypes: Sequence[str] = ("float32",)
    kwargs: Dict = {}
    grad_inputs: Optional[Sequence[int]] = None
    thresholds: Dict[str, Dict] = {}

    def setup(self):  # pragma: no cover - abstract
        raise NotImplementedError

    # -- machinery -------------------------------------------------------
    def _thr(self, dtype):
        thr = dict(DTYPE_THRESHOLDS[dtype])
        thr.update(self.thresholds.get(dtype, {}))
        return thr

    def _run_op(self, arrays, dtype):
        op = OPS[self.op_type]
        tensors = [paddle.to_tensor(a.astype(dtype)) for a in arrays]
        for t in tensors:
            t.stop_gradient = False
        out = op(*tensors, **self.kwargs)
        return tensors, out

    @staticmethod
    def _leaves(out) -> List[Tensor]:
        import jax

        return [t for t in jax.tree.leaves(
            out, is_leaf=lambda x: isinstance(x, Tensor))
            if isinstance(t, Tensor)]

    def check_output(self, dtype: Optional[str] = None):
        self.setup()
        for dt in ([dtype] if dtype else self.dtypes):
            thr = self._thr(dt)
            _, out = self._run_op(self.inputs, dt)
            ref = self.np_ref(*[a.astype(dt if dt != "bfloat16"
                                         else "float32")
                                for a in self.inputs])
            refs = ref if isinstance(ref, (tuple, list)) else [ref]
            outs = self._leaves(out)
            assert len(outs) == len(refs), (
                f"{self.op_type}: {len(outs)} outputs vs {len(refs)} refs")
            for o, r in zip(outs, refs):
                np.testing.assert_allclose(
                    np.asarray(o._data, dtype=np.float32),
                    np.asarray(r, dtype=np.float32),
                    rtol=thr["rtol"], atol=thr["atol"],
                    err_msg=f"{self.op_type}[{dt}] output mismatch")

    def check_grad(self, dtype: str = "float32", eps: float = 1e-3):
        """Analytic (tape) vs central-difference numeric gradients of
        sum(outputs) — reference: get_numeric_gradient (op_test.py:148)."""
        self.setup()
        thr = self._thr(dtype)
        which = (self.grad_inputs if self.grad_inputs is not None
                 else range(len(self.inputs)))

        # weighted loss sum(out * W): a plain sum degenerates for ops whose
        # outputs have an invariant (softmax rows sum to 1 → zero gradient)
        import paddle_tpu.core.dtype as dtype_mod

        def _weights(out):
            ws = []
            r = np.random.RandomState(123)
            for o in self._leaves(out):
                if dtype_mod.is_inexact_dtype(o._data.dtype):
                    ws.append(r.uniform(0.5, 1.5,
                                        np.asarray(o._data).shape))
                else:
                    ws.append(None)
            return ws

        tensors, out = self._run_op(self.inputs, dtype)
        weights = _weights(out)
        loss = None
        for o, w in zip(self._leaves(out), weights):
            if w is None:
                continue
            s = (o * paddle.to_tensor(w.astype(np.float32))).sum()
            loss = s if loss is None else loss + s
        assert loss is not None, f"{self.op_type}: no differentiable output"
        loss.backward()

        def fwd_sum(arrays):
            _, out = self._run_op(arrays, dtype)
            total = 0.0
            for o, w in zip(self._leaves(out), weights):
                if w is not None:
                    total += float((np.asarray(o._data, np.float64)
                                    * w).sum())
            return total

        for i in which:
            analytic = tensors[i].grad
            assert analytic is not None, (
                f"{self.op_type}: no grad for input {i}")
            a = np.asarray(analytic._data, np.float64)
            numeric = np.zeros_like(self.inputs[i], dtype=np.float64)
            flat = self.inputs[i].reshape(-1)
            num_flat = numeric.reshape(-1)
            for j in range(flat.size):
                orig = flat[j]
                arrays_p = [x.copy() for x in self.inputs]
                arrays_p[i].reshape(-1)[j] = orig + eps
                arrays_m = [x.copy() for x in self.inputs]
                arrays_m[i].reshape(-1)[j] = orig - eps
                num_flat[j] = (fwd_sum(arrays_p) - fwd_sum(arrays_m)) / (
                    2 * eps)
            scale = max(np.abs(numeric).max(), np.abs(a).max(), 1e-3)
            np.testing.assert_allclose(
                a, numeric, rtol=thr["grad_rtol"],
                atol=thr["grad_rtol"] * scale,
                err_msg=f"{self.op_type}[{dtype}] grad mismatch input {i}")

"""Pallas flash-attention kernel vs the XLA reference path.

Runs the kernel in Pallas interpreter mode on CPU (the fake-backend strategy
of SURVEY.md §4). Interpret mode skips Mosaic's block-mapping validation
(which is what let the round-2 lse BlockSpec bug reach the chip), so the
kernel mirrors that rule statically (`fa._assert_mosaic_tileable`, exercised
at every trace) and `test_mosaic_tiling_rule*` below pins the regression.
The kernel was verified end-to-end (lower+compile+run, fwd+bwd, GQA) on a
real TPU v5e chip on 2026-07-29; bench.py re-checks lowering every run.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import flash_attention as fa


def ref_attention(q, k, v, causal=True):
    """Plain einsum attention (the model's XLA path), f32."""
    B, T, H, hd = q.shape
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scale = 1.0 / (hd ** 0.5)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32)).astype(q.dtype)


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    B, T, H, hd = 2, 128, 4, 64
    q = _rand((B, T, H, hd), 0)
    k = _rand((B, T, H, hd), 1)
    v = _rand((B, T, H, hd), 2)
    out = fa.flash_attention(q, k, v, causal=causal, interpret=True)
    ref = ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_gqa():
    B, T, H, KV, hd = 2, 64, 8, 2, 32
    q = _rand((B, T, H, hd), 0)
    k = _rand((B, T, KV, hd), 1)
    v = _rand((B, T, KV, hd), 2)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_multi_block_seq():
    """T spans several kv blocks so the online-softmax rescaling is exercised."""
    B, T, H, hd = 1, 512, 2, 64
    q = _rand((B, T, H, hd), 3)
    k = _rand((B, T, H, hd), 4)
    v = _rand((B, T, H, hd), 5)
    out = fa.flash_attention(q, k, v, causal=True, interpret=True)
    ref = ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    B, T, H, hd = 1, 128, 2, 32
    q = _rand((B, T, H, hd), 6)
    k = _rand((B, T, H, hd), 7)
    v = _rand((B, T, H, hd), 8)

    def loss_flash(q, k, v):
        o = fa.flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(jnp.sin(o))  # non-trivial cotangent

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(ref_attention(q, k, v, causal=causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-4, atol=5e-5, err_msg=name)


def test_gradients_gqa():
    B, T, H, KV, hd = 1, 64, 4, 2, 32
    q = _rand((B, T, H, hd), 9)
    k = _rand((B, T, KV, hd), 10)
    v = _rand((B, T, KV, hd), 11)

    def loss_flash(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ref_attention(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-4, atol=5e-5, err_msg=name)


def test_bf16_inputs():
    B, T, H, hd = 1, 128, 2, 64
    q = _rand((B, T, H, hd), 12, jnp.bfloat16)
    k = _rand((B, T, H, hd), 13, jnp.bfloat16)
    v = _rand((B, T, H, hd), 14, jnp.bfloat16)
    out = fa.flash_attention(q, k, v, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = ref_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_unsupported_shapes_raise():
    q = jnp.zeros((1, 100, 3, 16))  # T=100 not tileable; H=3 not mult of KV=2
    k = jnp.zeros((1, 100, 2, 16))
    with pytest.raises(ValueError):
        fa.flash_attention(q, k, jnp.zeros_like(k), interpret=True)


def test_inside_jit_and_scan():
    """Kernel must be traceable inside jit + scan (the model's usage)."""
    B, T, H, hd = 1, 64, 2, 32
    q = _rand((B, T, H, hd), 15)
    k = _rand((B, T, H, hd), 16)
    v = _rand((B, T, H, hd), 17)

    @jax.jit
    def f(q, k, v):
        def body(carry, _):
            o = fa.flash_attention(carry, k, v, interpret=True)
            return o, None
        out, _ = jax.lax.scan(body, q, None, length=2)
        return out

    out = f(q, k, v)
    ref = ref_attention(ref_attention(q, k, v), k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_mosaic_tiling_rule_rejects_rank3_lse():
    # The exact BENCH_r02 failure: lse [B, H, T] with block (1, 1, bq) puts a
    # size-1 second-minor dim against H != 1. Must be rejected statically.
    with pytest.raises(ValueError, match="8, 128"):
        fa._assert_mosaic_tileable((1, 1, 256), (4, 12, 2048), "lse")


def test_mosaic_tiling_rule_accepts_current_layouts():
    # o block: last dim == array dim; second-minor divisible by 8
    fa._assert_mosaic_tileable((1, 1, 256, 128), (4, 12, 2048, 128), "o")
    # lse lane-broadcast block: last dim == array dim (LANES)
    fa._assert_mosaic_tileable((1, 1, 256, fa.LANES), (4, 12, 2048, fa.LANES),
                               "lse")


def test_kernel_constants_are_f32():
    # Under jax_enable_x64 a bare python float is weak f64 and the resulting
    # f64->f32 convert fails Mosaic legalization (tpu.truncf). Pin the dtype.
    assert np.asarray(fa.NEG_INF).dtype == np.float32
